"""LMD-GHOST fork choice (L3).

Equivalent of /root/reference/consensus/fork_choice (spec wrapper: queued
attestations, unrealized justification, proposer boost, invalid-payload
handling) + consensus/proto_array (flat node array, weight deltas, find_head,
pruning).
"""
from .proto_array import (
    ProtoArray, ProtoNode, ExecutionStatus, ProtoArrayError, VoteTracker,
    compute_deltas,
)
from .fork_choice import ForkChoice, ForkChoiceError, QueuedAttestation
