"""KZG polynomial commitments for EIP-4844 blobs.

Equivalent of /root/reference/crypto/kzg (wrapper over c-kzg): blob ->
commitment, opening proofs, single + batch verification — implemented on our
own BLS12-381 (pairing check e(proof, [tau - z]_2) == e(C - [y]_1, g_2)).

Trusted setup: the real ceremony file is not bundled (zero-egress image); a
deterministic DEVNET setup derived from a public seed is generated on first
use and is clearly INSECURE-FOR-PRODUCTION (anyone can recover tau). Load a
real setup with `load_trusted_setup(points)` for mainnet use.
"""
from __future__ import annotations

import hashlib

from .bls12_381 import (
    G1_GENERATOR, G2_GENERATOR, g1_compress, g1_decompress, multi_pairing,
)
from .bls12_381.curve import B_G1, Point
from .bls12_381.fields import R

FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_FIELD_ELEMENT = 32

#: primitive root of unity of order 4096 in the scalar field
_ROOT_OF_UNITY = pow(7, (R - 1) // FIELD_ELEMENTS_PER_BLOB, R)


class KzgError(Exception):
    pass


class Kzg:
    """One instance per trusted setup (kzg::Kzg, crypto/kzg/src/lib.rs:55)."""

    def __init__(self, g1_points: list | None = None, tau_g2=None,
                 devnet_size: int = 64):
        if g1_points is None:
            # INSECURE devnet setup: tau derived from a fixed public seed
            tau = int.from_bytes(hashlib.sha256(
                b"lighthouse-tpu-devnet-kzg-setup").digest(), "big") % R
            self.size = devnet_size
            self.g1 = [G1_GENERATOR.mul(pow(tau, i, R))
                       for i in range(self.size)]
            self.tau_g2 = G2_GENERATOR.mul(tau)
            self.insecure = True
        else:
            self.g1 = g1_points
            self.size = len(g1_points)
            self.tau_g2 = tau_g2
            self.insecure = False
        self.domain = [pow(_ROOT_OF_UNITY, _brp(i, FIELD_ELEMENTS_PER_BLOB),
                           R) for i in range(self.size)]

    # -- polynomial helpers (evaluation form over the bit-reversed domain) ---

    def _evals_from_blob(self, blob: bytes) -> list[int]:
        n = len(blob) // BYTES_PER_FIELD_ELEMENT
        if n > self.size:
            raise KzgError(f"blob larger than setup ({n} > {self.size})")
        out = []
        for i in range(n):
            v = int.from_bytes(
                blob[i * 32:(i + 1) * 32], "big")
            if v >= R:
                raise KzgError("blob element not canonical")
            out.append(v)
        # pad to setup size with zeros
        out += [0] * (self.size - n)
        return out

    def _root(self) -> int:
        """Primitive root of order self.size (the domain subgroup)."""
        return pow(_ROOT_OF_UNITY, FIELD_ELEMENTS_PER_BLOB // self.size, R)

    def _ntt(self, vals: list[int], invert: bool) -> list[int]:
        """Iterative radix-2 NTT over standard order (O(n log n) — the
        round-1 O(n^2) Lagrange interpolation is gone)."""
        n = len(vals)
        a = list(vals)
        # bit-reversal permutation to start the butterflies
        j = 0
        for i in range(1, n):
            bit = n >> 1
            while j & bit:
                j ^= bit
                bit >>= 1
            j |= bit
            if i < j:
                a[i], a[j] = a[j], a[i]
        root = self._root()
        if invert:
            root = pow(root, R - 2, R)
        length = 2
        while length <= n:
            wlen = pow(root, n // length, R)
            for i in range(0, n, length):
                w = 1
                half = length // 2
                for k in range(i, i + half):
                    u, v = a[k], a[k + half] * w % R
                    a[k] = (u + v) % R
                    a[k + half] = (u - v) % R
                    w = w * wlen % R
            length <<= 1
        if invert:
            ninv = pow(n, R - 2, R)
            a = [x * ninv % R for x in a]
        return a

    def _coeffs(self, evals: list[int]) -> list[int]:
        """Monomial coefficients from evaluations over the bit-reversed
        domain: un-permute (brp is an involution) then inverse NTT."""
        n = self.size
        std = [0] * n
        for i, v in enumerate(evals):
            std[_brp(i, n)] = v
        return self._ntt(std, invert=True)

    def _eval_barycentric(self, evals: list[int], z: int) -> int:
        """p(z) from evaluation form without interpolation (the spec's
        evaluate_polynomial_in_evaluation_form):
        p(z) = (z^n - 1)/n * sum_i evals_i * d_i / (z - d_i)."""
        n = self.size
        for i, d in enumerate(self.domain):
            if d == z % R:
                return evals[i]
        diffs = [(z - d) % R for d in self.domain]
        invs = _batch_inverse(diffs)
        acc = 0
        for e, d, inv in zip(evals, self.domain, invs):
            if e:
                acc = (acc + e * d % R * inv) % R
        zn = (pow(z, n, R) - 1) % R
        return acc * zn % R * pow(n, R - 2, R) % R

    def _commit_coeffs(self, coeffs: list[int]) -> Point:
        acc = Point.infinity(B_G1)
        for c, p in zip(coeffs, self.g1):
            if c:
                acc = acc.add(p.mul(c))
        return acc

    # -- public API (c-kzg surface) ------------------------------------------

    def blob_to_kzg_commitment(self, blob: bytes) -> bytes:
        return g1_compress(self._commit_coeffs(
            self._coeffs(self._evals_from_blob(blob))))

    def compute_kzg_proof(self, blob: bytes, z: int) -> tuple[bytes, int]:
        """Proof that p(z) == y; returns (proof, y)."""
        coeffs = self._coeffs(self._evals_from_blob(blob))
        y = _poly_eval(coeffs, z)
        # q(x) = (p(x) - y) / (x - z)
        q = _poly_div_linear(coeffs, y, z)
        return g1_compress(self._commit_coeffs(q)), y

    def verify_kzg_proof(self, commitment: bytes, z: int, y: int,
                         proof: bytes) -> bool:
        c = g1_decompress(commitment)
        w = g1_decompress(proof)
        if c is None or w is None:
            return False
        # e(W, [tau]_2 - [z]_2) == e(C - [y]_1, g2)
        tau_minus_z = self.tau_g2.add(G2_GENERATOR.mul(z).neg())
        c_minus_y = c.add(G1_GENERATOR.mul(y).neg())
        return multi_pairing([
            (w, tau_minus_z),
            (c_minus_y.neg(), G2_GENERATOR),
        ]).is_one()

    def compute_blob_kzg_proof(self, blob: bytes,
                               commitment: bytes) -> bytes:
        z = _challenge(blob, commitment)
        proof, _y = self.compute_kzg_proof(blob, z)
        return proof

    def verify_blob_kzg_proof(self, blob: bytes, commitment: bytes,
                              proof: bytes) -> bool:
        z = _challenge(blob, commitment)
        y = self._eval_barycentric(self._evals_from_blob(blob), z)
        return self.verify_kzg_proof(commitment, z, y, proof)

    def verify_blob_kzg_proof_batch(self, blobs: list[bytes],
                                    commitments: list[bytes],
                                    proofs: list[bytes]) -> bool:
        """ONE 2-pairing check for the whole batch via a random linear
        combination (c-kzg verify_blob_kzg_proof_batch):
          e(sum r_i pi_i, [tau]_2) * e(-sum r_i (C_i - y_i G + z_i pi_i),
            g_2) == 1
        The deneb 6-blob sidecar batch costs the same two pairings as one
        blob (round 1 paid n pairing-pairs)."""
        import secrets
        if not (len(blobs) == len(commitments) == len(proofs)):
            return False
        if not blobs:
            return True
        agg_proof = Point.infinity(B_G1)
        agg_rest = Point.infinity(B_G1)
        for blob, comm, prf in zip(blobs, commitments, proofs):
            c = g1_decompress(comm)
            w = g1_decompress(prf)
            if c is None or w is None:
                return False
            z = _challenge(blob, comm)
            y = self._eval_barycentric(self._evals_from_blob(blob), z)
            r = 1 if len(blobs) == 1 else secrets.randbits(128) | 1
            agg_proof = agg_proof.add(w.mul(r))
            rest = c.add(G1_GENERATOR.mul(y).neg()).add(w.mul(z))
            agg_rest = agg_rest.add(rest.mul(r))
        return multi_pairing([
            (agg_proof, self.tau_g2),
            (agg_rest.neg(), G2_GENERATOR),
        ]).is_one()


def _batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery batch inversion: one field inversion for the lot."""
    prefix = [1] * (len(vals) + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * v % R
    inv = pow(prefix[-1], R - 2, R)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = prefix[i] * inv % R
        inv = inv * vals[i] % R
    return out


def _brp(i: int, n: int) -> int:
    bits = n.bit_length() - 1
    return int(bin(i)[2:].zfill(bits)[::-1], 2)


def _poly_mul_linear(poly: list[int], c: int) -> list[int]:
    """poly(x) * (x + c) mod R."""
    out = [0] * (len(poly) + 1)
    for i, a in enumerate(poly):
        out[i] = (out[i] + a * c) % R
        out[i + 1] = (out[i + 1] + a) % R
    return out


def _poly_eval(coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc


def _poly_div_linear(coeffs: list[int], y: int, z: int) -> list[int]:
    """(p(x) - y) / (x - z) via synthetic division (exact when p(z) == y)."""
    n = len(coeffs)
    q = [0] * (n - 1)
    acc = 0
    for i in range(n - 1, 0, -1):
        acc = (coeffs[i] + z * acc) % R
        q[i - 1] = acc
    return q


def _challenge(blob: bytes, commitment: bytes) -> int:
    """Fiat-Shamir evaluation challenge (spec compute_challenge shape)."""
    h = hashlib.sha256(b"LHTPU_KZG_CHALLENGE" + len(blob).to_bytes(8, "little")
                       + blob + commitment).digest()
    return int.from_bytes(h, "big") % R
