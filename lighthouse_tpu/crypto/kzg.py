"""KZG polynomial commitments for EIP-4844 blobs and EIP-7594 cells.

Equivalent of /root/reference/crypto/kzg (wrapper over c-kzg): blob ->
commitment, opening proofs, single + batch verification — implemented on our
own BLS12-381 (pairing check e(proof, [tau - z]_2) == e(C - [y]_1, g_2)) —
plus the PeerDAS cells surface (compute_cells_and_kzg_proofs /
verify_cell_kzg_proof_batch / recover_cells_and_kzg_proofs): the blob's
polynomial is Reed-Solomon extended to a 2n-point evaluation domain split
into cosets ("cells"), each cell carrying a KZG multi-point opening proof,
and any half of the cells recovers the rest (c-kzg `Cell`,
crypto/kzg/src/lib.rs:31 CELLS_PER_EXT_BLOB).

Group arithmetic rides the native C++ host library when available
(native/bls12_381.cpp `kzg_g1_msm` / `kzg_pairing_check` — the c-kzg
equivalent of SURVEY.md §2.6) and falls back to the pure-Python oracle.

Trusted setup: the real ceremony file is not bundled (zero-egress image); a
deterministic DEVNET setup derived from a public seed is generated on first
use and is clearly INSECURE-FOR-PRODUCTION (anyone can recover tau). Load a
real setup by constructing `Kzg(g1_points, tau_g2, g2_powers=...)`.
"""
from __future__ import annotations

import hashlib

from .bls12_381 import (
    G1_GENERATOR, G2_GENERATOR, g1_compress, g1_decompress, g2_compress,
    multi_pairing,
)
from .bls12_381.curve import B_G1, Point
from .bls12_381.fields import R
from ..specs.constants import BYTES_PER_FIELD_ELEMENT  # single source of truth

FIELD_ELEMENTS_PER_BLOB = 4096
#: spec cell count over the 2x-extended blob (CELLS_PER_EXT_BLOB); clamped
#: to the extended domain size for small devnet setups
CELLS_PER_EXT_BLOB = 128

#: primitive root of unity of order 4096 in the scalar field
_ROOT_OF_UNITY = pow(7, (R - 1) // FIELD_ELEMENTS_PER_BLOB, R)

_G1_GEN_COMP = g1_compress(G1_GENERATOR)


class KzgError(Exception):
    pass


_NATIVE = None


def _native():
    """The C++ host library, or None (pure-Python fallback)."""
    global _NATIVE
    if _NATIVE is None:
        try:
            from .bls.cpp_backend import get_lib
            lib = get_lib()
            lib.kzg_g1_msm  # raises AttributeError on a stale .so
            _NATIVE = lib
        except Exception:
            _NATIVE = False
    return _NATIVE or None


def _msm(scalars: list[int], points_comp: list[bytes]) -> Point:
    """sum scalars[i] * decompress(points_comp[i]) — native when possible."""
    import ctypes
    pairs = [(s % R, p) for s, p in zip(scalars, points_comp) if s % R]
    if not pairs:
        return Point.infinity(B_G1)
    lib = _native()
    if lib is not None:
        sc = b"".join(s.to_bytes(32, "big") for s, _ in pairs)
        pts = b"".join(p for _, p in pairs)
        out = ctypes.create_string_buffer(48)
        if lib.kzg_g1_msm(len(pairs), sc, pts, out) == 0:
            res = g1_decompress(out.raw)
            if res is not None:
                return res
    acc = Point.infinity(B_G1)
    for s, p in pairs:
        pt = g1_decompress(p)
        if pt is None:
            raise KzgError("bad point in MSM")
        acc = acc.add(pt.mul(s))
    return acc


def _pairing_is_one(pairs: list[tuple[Point, Point]]) -> bool:
    """prod e(a_i, b_i) == 1 — native multi-pairing when possible."""
    lib = _native()
    if lib is not None:
        g1s = b"".join(g1_compress(a) for a, _ in pairs)
        g2s = b"".join(g2_compress(b) for _, b in pairs)
        rc = lib.kzg_pairing_check(len(pairs), g1s, g2s)
        if rc >= 0:
            return rc == 1
    return multi_pairing(pairs).is_one()


class Kzg:
    """One instance per trusted setup (kzg::Kzg, crypto/kzg/src/lib.rs:55)."""

    def __init__(self, g1_points: list | None = None, tau_g2=None,
                 devnet_size: int = 64, g2_powers: list | None = None,
                 cells_per_ext_blob: int = CELLS_PER_EXT_BLOB):
        if g1_points is None:
            # INSECURE devnet setup: tau derived from a fixed public seed
            tau = int.from_bytes(hashlib.sha256(
                b"lighthouse-tpu-devnet-kzg-setup").digest(), "big") % R
            self.size = devnet_size
            self.g1 = [G1_GENERATOR.mul(pow(tau, i, R))
                       for i in range(self.size)]
            self.tau_g2 = G2_GENERATOR.mul(tau)
            self.insecure = True
            self._tau = tau
        else:
            self.g1 = g1_points
            self.size = len(g1_points)
            self.tau_g2 = tau_g2
            self.insecure = False
            self._tau = None
        #: [tau^i]_2 for the cells multi-point check (real ceremony files
        #: carry 65 G2 points); devnet derives what it needs from tau
        self.g2_powers = g2_powers
        self._cells_req = cells_per_ext_blob
        self._cells_cfg_cache = None
        self._g1_comp = None
        self.domain = [pow(_ROOT_OF_UNITY, _brp(i, FIELD_ELEMENTS_PER_BLOB),
                           R) for i in range(self.size)]

    @property
    def g1_comp(self) -> list[bytes]:
        """Compressed setup points (native-MSM operand), built once."""
        if self._g1_comp is None:
            self._g1_comp = [g1_compress(p) for p in self.g1]
        return self._g1_comp

    # -- polynomial helpers (evaluation form over the bit-reversed domain) ---

    def _evals_from_blob(self, blob: bytes) -> list[int]:
        n = len(blob) // BYTES_PER_FIELD_ELEMENT
        if n > self.size:
            raise KzgError(f"blob larger than setup ({n} > {self.size})")
        out = []
        for i in range(n):
            v = int.from_bytes(
                blob[i * 32:(i + 1) * 32], "big")
            if v >= R:
                raise KzgError("blob element not canonical")
            out.append(v)
        # pad to setup size with zeros
        out += [0] * (self.size - n)
        return out

    def _root(self) -> int:
        """Primitive root of order self.size (the domain subgroup)."""
        return pow(_ROOT_OF_UNITY, FIELD_ELEMENTS_PER_BLOB // self.size, R)

    def _ntt(self, vals: list[int], invert: bool) -> list[int]:
        return _ntt_with_root(vals, self._root(), invert)

    def _coeffs(self, evals: list[int]) -> list[int]:
        """Monomial coefficients from evaluations over the bit-reversed
        domain: un-permute (brp is an involution) then inverse NTT."""
        n = self.size
        std = [0] * n
        for i, v in enumerate(evals):
            std[_brp(i, n)] = v
        return self._ntt(std, invert=True)

    def _eval_barycentric(self, evals: list[int], z: int) -> int:
        """p(z) from evaluation form without interpolation (the spec's
        evaluate_polynomial_in_evaluation_form):
        p(z) = (z^n - 1)/n * sum_i evals_i * d_i / (z - d_i)."""
        n = self.size
        for i, d in enumerate(self.domain):
            if d == z % R:
                return evals[i]
        diffs = [(z - d) % R for d in self.domain]
        invs = _batch_inverse(diffs)
        acc = 0
        for e, d, inv in zip(evals, self.domain, invs):
            if e:
                acc = (acc + e * d % R * inv) % R
        zn = (pow(z, n, R) - 1) % R
        return acc * zn % R * pow(n, R - 2, R) % R

    def _commit_coeffs(self, coeffs: list[int]) -> Point:
        if _native() is not None:
            return _msm(list(coeffs), self.g1_comp[:len(coeffs)])
        acc = Point.infinity(B_G1)
        for c, p in zip(coeffs, self.g1):
            if c:
                acc = acc.add(p.mul(c))
        return acc

    # -- public API (c-kzg surface) ------------------------------------------

    def blob_to_kzg_commitment(self, blob: bytes) -> bytes:
        return g1_compress(self._commit_coeffs(
            self._coeffs(self._evals_from_blob(blob))))

    def compute_kzg_proof(self, blob: bytes, z: int) -> tuple[bytes, int]:
        """Proof that p(z) == y; returns (proof, y)."""
        coeffs = self._coeffs(self._evals_from_blob(blob))
        y = _poly_eval(coeffs, z)
        # q(x) = (p(x) - y) / (x - z)
        q = _poly_div_linear(coeffs, y, z)
        return g1_compress(self._commit_coeffs(q)), y

    def verify_kzg_proof(self, commitment: bytes, z: int, y: int,
                         proof: bytes) -> bool:
        c = g1_decompress(commitment)
        w = g1_decompress(proof)
        if c is None or w is None:
            return False
        # e(W, [tau]_2 - [z]_2) == e(C - [y]_1, g2), rearranged so all the
        # per-proof arithmetic stays in G1:
        #   e(W, [tau]_2) * e(-z*W - C + y*G, g2) == 1
        x = _msm([(-z) % R, R - 1, y % R],
                 [bytes(proof), bytes(commitment), _G1_GEN_COMP])
        return _pairing_is_one([(w, self.tau_g2), (x, G2_GENERATOR)])

    def compute_blob_kzg_proof(self, blob: bytes,
                               commitment: bytes) -> bytes:
        z = _challenge(blob, commitment)
        proof, _y = self.compute_kzg_proof(blob, z)
        return proof

    def verify_blob_kzg_proof(self, blob: bytes, commitment: bytes,
                              proof: bytes) -> bool:
        z = _challenge(blob, commitment)
        y = self._eval_barycentric(self._evals_from_blob(blob), z)
        return self.verify_kzg_proof(commitment, z, y, proof)

    def verify_blob_kzg_proof_batch(self, blobs: list[bytes],
                                    commitments: list[bytes],
                                    proofs: list[bytes]) -> bool:
        """ONE 2-pairing check for the whole batch via a random linear
        combination (c-kzg verify_blob_kzg_proof_batch):
          e(sum r_i pi_i, [tau]_2) * e(-sum r_i (C_i - y_i G + z_i pi_i),
            g_2) == 1
        The deneb 6-blob sidecar batch costs the same two pairings as one
        blob (round 1 paid n pairing-pairs)."""
        import secrets
        if not (len(blobs) == len(commitments) == len(proofs)):
            return False
        if not blobs:
            return True
        # aggregate everything into two MSMs and one 2-pairing check
        scalars, points = [], []      # -> agg_rest = -sum r(C - yG + zW)
        pscalars, ppoints = [], []    # -> agg_proof = sum r*W
        y_gen = 0
        for blob, comm, prf in zip(blobs, commitments, proofs):
            # on-curve pre-check; the RLC aggregate is subgroup-checked
            # inside the pairing check
            if (g1_decompress(comm, subgroup_check=False) is None
                    or g1_decompress(prf, subgroup_check=False) is None):
                return False
            z = _challenge(blob, comm)
            y = self._eval_barycentric(self._evals_from_blob(blob), z)
            r = 1 if len(blobs) == 1 else secrets.randbits(128) | 1
            pscalars.append(r)
            ppoints.append(bytes(prf))
            scalars += [(-r) % R, (-r * z) % R]
            points += [bytes(comm), bytes(prf)]
            y_gen = (y_gen + r * y) % R
        scalars.append(y_gen)
        points.append(_G1_GEN_COMP)
        agg_proof = _msm(pscalars, ppoints)
        agg_rest = _msm(scalars, points)
        return _pairing_is_one([
            (agg_proof, self.tau_g2),
            (agg_rest, G2_GENERATOR),
        ])

    # -- EIP-7594 cells (PeerDAS; c-kzg compute/verify/recover_cells) --------

    def _cells_cfg(self):
        """Lazily derived extended-domain/coset structure.

        The polynomial (degree < n) is evaluated over the 2n-point
        extension domain, split in bit-reversal order into `cells` cosets
        of l = 2n/cells points each: cell i holds p on h_i*H where
        H = <w^cells> (order l) and h_i = w^brp(i, cells).
        """
        if self._cells_cfg_cache is not None:
            return self._cells_cfg_cache
        n = self.size
        ext = 2 * n
        cells = min(self._cells_req, ext)
        ell = ext // cells
        w = pow(7, (R - 1) // ext, R)        # root of order 2n
        h = [pow(w, _brp(i, cells), R) for i in range(cells)]
        # [tau^l]_2 for the multi-point check
        if self.g2_powers is not None:
            if len(self.g2_powers) <= ell:
                raise KzgError("trusted setup lacks [tau^l]_2")
            tau_l_g2 = self.g2_powers[ell]
        elif self._tau is not None:
            tau_l_g2 = G2_GENERATOR.mul(pow(self._tau, ell, R))
        else:
            raise KzgError("setup has no G2 powers for cell proofs")
        cfg = (ext, cells, ell, w, h, tau_l_g2)
        self._cells_cfg_cache = cfg
        return cfg

    @property
    def cells_per_ext_blob(self) -> int:
        return self._cells_cfg()[1]

    def _ext_evals_std(self, coeffs: list[int]) -> list[int]:
        ext, _, _, w, _, _ = self._cells_cfg()
        return _ntt_with_root(list(coeffs) + [0] * (ext - len(coeffs)),
                              w, invert=False)

    def _cells_from_coeffs(self, coeffs: list[int]) -> list[bytes]:
        _, cells, ell, _, _, _ = self._cells_cfg()
        ev = self._ext_evals_std(coeffs)
        out = []
        for i in range(cells):
            vals = [ev[_brp(j, ell) * cells + _brp(i, cells)]
                    for j in range(ell)]
            out.append(b"".join(v.to_bytes(32, "big") for v in vals))
        return out

    def _cell_values(self, cell: bytes) -> list[int]:
        _, _, ell, _, _, _ = self._cells_cfg()
        if len(cell) != 32 * ell:
            raise KzgError("bad cell size")
        vals = [int.from_bytes(cell[32 * j:32 * (j + 1)], "big")
                for j in range(ell)]
        if any(v >= R for v in vals):
            raise KzgError("cell element not canonical")
        return vals

    def _cell_interpolant(self, index: int, vals: list[int]) -> list[int]:
        """Coefficients (degree < l) of the cell's interpolant r_i:
        r_i(h_i * y) over H is a size-l inverse NTT, then unscale by
        h_i^-m."""
        _, cells, ell, w, h, _ = self._cells_cfg()
        if ell == 1:
            return [vals[0]]
        wl = pow(w, cells, R)                 # root of order l
        std = [0] * ell
        for k in range(ell):
            std[k] = vals[_brp(k, ell)]
        sc = _ntt_with_root(std, wl, invert=True)
        hinv = pow(h[index], R - 2, R)
        out, f = [], 1
        for m in range(ell):
            out.append(sc[m] * f % R)
            f = f * hinv % R
        return out

    def _cell_proof(self, coeffs: list[int], index: int,
                    r_coeffs: list[int]) -> bytes:
        """pi_i = [q_i(tau)]_1, q_i = (p - r_i) / (x^l - h_i^l)."""
        n, (_, _, ell, _, h, _) = self.size, self._cells_cfg()
        a = pow(h[index], ell, R)
        d = list(coeffs) + [0] * (n - len(coeffs))
        for m, rm in enumerate(r_coeffs):
            d[m] = (d[m] - rm) % R
        q = [0] * (n - ell)
        for k in range(n - ell - 1, -1, -1):
            t = d[k + ell]
            if k + ell < n - ell:
                t += a * q[k + ell]
            q[k] = t % R
        return g1_compress(self._commit_coeffs(q))

    def compute_cells(self, blob: bytes) -> list[bytes]:
        return self._cells_from_coeffs(
            self._coeffs(self._evals_from_blob(blob)))

    def compute_cells_and_kzg_proofs(
            self, blob: bytes) -> tuple[list[bytes], list[bytes]]:
        coeffs = self._coeffs(self._evals_from_blob(blob))
        return self._cells_and_proofs_from_coeffs(coeffs)

    def _cells_and_proofs_from_coeffs(self, coeffs):
        _, cells, ell, _, _, _ = self._cells_cfg()
        out_cells = self._cells_from_coeffs(coeffs)
        proofs = []
        for i in range(cells):
            r = self._cell_interpolant(i, self._cell_values(out_cells[i]))
            proofs.append(self._cell_proof(coeffs, i, r))
        return out_cells, proofs

    def verify_cell_kzg_proof_batch(self, commitments: list[bytes],
                                    cell_indices: list[int],
                                    cells: list[bytes],
                                    proofs: list[bytes]) -> bool:
        """ONE 2-pairing check for any mix of (commitment, cell) pairs via
        a random linear combination:
          e(sum r_i pi_i, [tau^l]_2)
            * e(sum r_i (-h_i^l pi_i + [interp_i(tau)]_1 - C_i), g2) == 1
        (per-cell: e(pi, [tau^l - h^l]_2) == e(C - [interp(tau)]_1, g2),
        rearranged so the aggregation stays in G1)."""
        import secrets
        if not (len(commitments) == len(cell_indices) == len(cells)
                == len(proofs)):
            return False
        if not cells:
            return True
        try:
            _, n_cells, ell, _, h, tau_l_g2 = self._cells_cfg()
            pscalars, ppoints = [], []     # sum r*pi
            scalars, points = [], []       # G1 side of the g2 pairing
            agg_interp = [0] * ell         # sum r * interp_i coefficients
            for comm, idx, cell, prf in zip(commitments, cell_indices,
                                            cells, proofs):
                if not (0 <= idx < n_cells):
                    return False
                # on-curve/format pre-check only: rogue-subgroup components
                # are caught w.h.p. by the subgroup check on the random
                # linear combination inside the pairing check
                if (g1_decompress(comm, subgroup_check=False) is None
                        or g1_decompress(prf, subgroup_check=False) is None):
                    return False
                vals = self._cell_values(bytes(cell))
                r_coeffs = self._cell_interpolant(idx, vals)
                rho = 1 if len(cells) == 1 else secrets.randbits(128) | 1
                a = pow(h[idx], ell, R)
                pscalars.append(rho)
                ppoints.append(bytes(prf))
                scalars += [(-rho * a) % R, (-rho) % R]
                points += [bytes(prf), bytes(comm)]
                for m in range(ell):
                    agg_interp[m] = (agg_interp[m] + rho * r_coeffs[m]) % R
            scalars += agg_interp
            points += self.g1_comp[:ell]
            return _pairing_is_one([
                (_msm(pscalars, ppoints), tau_l_g2),
                (_msm(scalars, points), G2_GENERATOR),
            ])
        except KzgError:
            return False

    def recover_cells_and_kzg_proofs(
            self, cell_indices: list[int],
            cells: list[bytes]) -> tuple[list[bytes], list[bytes]]:
        """Erasure-recover the full cell set (plus proofs) from any >= 50%
        of cells (spec recover_cells_and_kzg_proofs): multiply by the
        vanishing polynomial of the missing cosets, inverse-NTT, divide on
        a shifted domain, and re-extend."""
        coeffs = self.recover_polynomial_coeffs(cell_indices, cells)
        return self._cells_and_proofs_from_coeffs(coeffs)

    def recover_polynomial_coeffs(self, cell_indices: list[int],
                                  cells: list[bytes]) -> list[int]:
        ext, n_cells, ell, w, h, _ = self._cells_cfg()
        n = self.size
        known: dict[int, list[int]] = {}
        for idx, cell in zip(cell_indices, cells):
            if not (0 <= idx < n_cells):
                raise KzgError("cell index out of range")
            known[int(idx)] = self._cell_values(bytes(cell))
        if len(known) * ell < n:
            raise KzgError(
                f"need >= {n // ell} cells to recover, have {len(known)}")
        missing = [i for i in range(n_cells) if i not in known]
        if not missing:
            ev = [0] * ext
            for i, vals in known.items():
                for j in range(ell):
                    ev[_brp(j, ell) * n_cells + _brp(i, n_cells)] = vals[j]
            coeffs = _ntt_with_root(ev, w, invert=True)
        else:
            # vanishing polynomial of the missing cosets, as a polynomial
            # in u = x^l: Z(x) = prod (x^l - h_m^l)
            zu = [1]
            for m in missing:
                zu = _poly_mul_linear(zu, (-pow(h[m], ell, R)) % R)
            z_coeffs = [0] * ext
            for k, v in enumerate(zu):
                z_coeffs[k * ell] = v
            z_ev = _ntt_with_root(z_coeffs, w, invert=False)
            # (E*Z) over the extension domain: 0 on missing cosets
            ez = [0] * ext
            for i, vals in known.items():
                for j in range(ell):
                    k = _brp(j, ell) * n_cells + _brp(i, n_cells)
                    ez[k] = vals[j] * z_ev[k] % R
            ez_coeffs = _ntt_with_root(ez, w, invert=True)
            # divide (E*Z)/Z on a shifted domain (Z has no roots there)
            shift = 7
            sh_pow, f = [], 1
            for _ in range(ext):
                sh_pow.append(f)
                f = f * shift % R
            num = _ntt_with_root(
                [c * s % R for c, s in zip(ez_coeffs, sh_pow)], w, False)
            den = _ntt_with_root(
                [c * s % R for c, s in zip(z_coeffs, sh_pow)], w, False)
            quo = [a * b % R
                   for a, b in zip(num, _batch_inverse(den))]
            q_shift = _ntt_with_root(quo, w, invert=True)
            sinv = pow(shift, R - 2, R)
            coeffs, f = [], 1
            for c in q_shift:
                coeffs.append(c * f % R)
                f = f * sinv % R
        if any(coeffs[n:]):
            raise KzgError("inconsistent cells (recovered degree >= n)")
        return coeffs[:n]

    def cells_to_blob(self, cells: list[bytes]) -> bytes:
        """The original blob is exactly the first half of the extension in
        bit-reversal order."""
        _, n_cells, _, _, _, _ = self._cells_cfg()
        if len(cells) < n_cells // 2:
            raise KzgError("need the first half of the cells")
        return b"".join(bytes(c) for c in cells[:n_cells // 2])

    def recover_blob(self, cell_indices: list[int],
                     cells: list[bytes]) -> bytes:
        """Blob bytes from any >= 50% of cells WITHOUT recomputing the
        per-cell proofs (the cheap path for column reconstruction)."""
        coeffs = self.recover_polynomial_coeffs(cell_indices, cells)
        return self.cells_to_blob(self._cells_from_coeffs(coeffs))


def _ntt_with_root(vals: list[int], root: int, invert: bool) -> list[int]:
    """Iterative radix-2 NTT over standard order, root of order len(vals)
    (O(n log n) — the round-1 O(n^2) Lagrange interpolation is gone)."""
    n = len(vals)
    a = list(vals)
    # bit-reversal permutation to start the butterflies
    j = 0
    for i in range(1, n):
        bit = n >> 1
        while j & bit:
            j ^= bit
            bit >>= 1
        j |= bit
        if i < j:
            a[i], a[j] = a[j], a[i]
    if invert:
        root = pow(root, R - 2, R)
    length = 2
    while length <= n:
        wlen = pow(root, n // length, R)
        for i in range(0, n, length):
            w = 1
            half = length // 2
            for k in range(i, i + half):
                u, v = a[k], a[k + half] * w % R
                a[k] = (u + v) % R
                a[k + half] = (u - v) % R
                w = w * wlen % R
        length <<= 1
    if invert:
        ninv = pow(n, R - 2, R)
        a = [x * ninv % R for x in a]
    return a


def _batch_inverse(vals: list[int]) -> list[int]:
    """Montgomery batch inversion: one field inversion for the lot."""
    prefix = [1] * (len(vals) + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * v % R
    inv = pow(prefix[-1], R - 2, R)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = prefix[i] * inv % R
        inv = inv * vals[i] % R
    return out


def _brp(i: int, n: int) -> int:
    bits = n.bit_length() - 1
    return int(bin(i)[2:].zfill(bits)[::-1], 2)


def _poly_mul_linear(poly: list[int], c: int) -> list[int]:
    """poly(x) * (x + c) mod R."""
    out = [0] * (len(poly) + 1)
    for i, a in enumerate(poly):
        out[i] = (out[i] + a * c) % R
        out[i + 1] = (out[i + 1] + a) % R
    return out


def _poly_eval(coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc


def _poly_div_linear(coeffs: list[int], y: int, z: int) -> list[int]:
    """(p(x) - y) / (x - z) via synthetic division (exact when p(z) == y)."""
    n = len(coeffs)
    q = [0] * (n - 1)
    acc = 0
    for i in range(n - 1, 0, -1):
        acc = (coeffs[i] + z * acc) % R
        q[i - 1] = acc
    return q


def _challenge(blob: bytes, commitment: bytes) -> int:
    """Fiat-Shamir evaluation challenge (spec compute_challenge shape)."""
    h = hashlib.sha256(b"LHTPU_KZG_CHALLENGE" + len(blob).to_bytes(8, "little")
                       + blob + commitment).digest()
    return int.from_bytes(h, "big") % R
