"""BLS12-381 curves: E/Fp: y^2 = x^3 + 4 (G1) and the M-type sextic twist
E'/Fp2: y^2 = x^3 + 4(1+u) (G2). Jacobian arithmetic, generic over the field.

Cofactors are *derived* from the curve parameter x at import time (and checked
for divisibility by r) rather than hardcoded, so every constant here is
self-validating.
"""
from __future__ import annotations

import math

from .fields import Fp, Fp2, P, R, X_PARAM

B_G1 = Fp(4)
B_G2 = Fp2(4, 4)


class Point:
    """Jacobian point on y^2 = x^3 + b over a generic field."""

    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x, y, z, b):
        self.x, self.y, self.z, self.b = x, y, z, b

    @classmethod
    def infinity(cls, b):
        one = _one_like(b)
        return cls(one, one, _zero_like(b), b)

    @classmethod
    def from_affine(cls, x, y, b):
        pt = cls(x, y, _one_like(b), b)
        return pt

    def is_infinity(self) -> bool:
        return _is_zero(self.z)

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        x, y = self.to_affine()
        return y * y == x * x * x + self.b

    def to_affine(self):
        assert not self.is_infinity()
        zinv = _inv(self.z)
        zinv2 = zinv * zinv
        return self.x * zinv2, self.y * (zinv2 * zinv)

    def double(self) -> "Point":
        if self.is_infinity():
            return self
        X, Y, Z = self.x, self.y, self.z
        A = X * X
        Bv = Y * Y
        C = Bv * Bv
        t = (X + Bv)
        D = (t * t - A - C) * 2
        E = A * 3
        F = E * E
        X3 = F - D * 2
        Y3 = E * (D - X3) - C * 8
        Z3 = (Y * Z) * 2
        return Point(X3, Y3, Z3, self.b)

    def add(self, o: "Point") -> "Point":
        if self.is_infinity():
            return o
        if o.is_infinity():
            return self
        Z1Z1 = self.z * self.z
        Z2Z2 = o.z * o.z
        U1 = self.x * Z2Z2
        U2 = o.x * Z1Z1
        S1 = self.y * (o.z * Z2Z2)
        S2 = o.y * (self.z * Z1Z1)
        if U1 == U2:
            if S1 == S2:
                return self.double()
            return Point.infinity(self.b)
        H = U2 - U1
        I = (H * 2) * (H * 2)
        J = H * I
        rr = (S2 - S1) * 2
        V = U1 * I
        X3 = rr * rr - J - V * 2
        Y3 = rr * (V - X3) - (S1 * J) * 2
        zsum = self.z + o.z
        Z3 = (zsum * zsum - Z1Z1 - Z2Z2) * H
        return Point(X3, Y3, Z3, self.b)

    def neg(self) -> "Point":
        return Point(self.x, -self.y, self.z, self.b)

    def mul(self, k: int) -> "Point":
        if k < 0:
            return self.neg().mul(-k)
        out = Point.infinity(self.b)
        base = self
        while k:
            if k & 1:
                out = out.add(base)
            base = base.double()
            k >>= 1
        return out

    def eq(self, o: "Point") -> bool:
        if self.is_infinity() or o.is_infinity():
            return self.is_infinity() and o.is_infinity()
        Z1Z1 = self.z * self.z
        Z2Z2 = o.z * o.z
        if self.x * Z2Z2 != o.x * Z1Z1:
            return False
        return self.y * (o.z * Z2Z2) == o.y * (self.z * Z1Z1)

    def in_subgroup(self) -> bool:
        return self.mul(R).is_infinity()


def _one_like(b):
    return Fp(1) if isinstance(b, Fp) else Fp2(1, 0)


def _zero_like(b):
    return Fp(0) if isinstance(b, Fp) else Fp2(0, 0)


def _is_zero(v) -> bool:
    return int(v) == 0 if isinstance(v, Fp) else v.is_zero()


def _inv(v):
    return v.inv()


def G1Point(x: int, y: int) -> Point:
    return Point.from_affine(Fp(x), Fp(y), B_G1)


def G2Point(x: Fp2, y: Fp2) -> Point:
    return Point.from_affine(x, y, B_G2)


# -- standard generators (checked on-curve + in-subgroup below) --------------

G1_GENERATOR = G1Point(
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)

G2_GENERATOR = G2Point(
    Fp2(0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    Fp2(0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


# -- cofactors derived from x ------------------------------------------------

def _derive_cofactors():
    t = X_PARAM + 1
    n1 = P + 1 - t
    assert n1 % R == 0
    h1 = n1 // R
    # order of the right sextic twist over Fp2
    t2 = t * t - 2 * P
    f2 = (4 * P * P - t2 * t2) // 3
    f = math.isqrt(f2)
    assert f * f == f2
    for n2 in (P * P + 1 - (t2 + 3 * f) // 2, P * P + 1 - (t2 - 3 * f) // 2):
        if n2 % R == 0:
            return h1, n2 // R
    raise AssertionError("no twist order divisible by r")


H_EFF_G1, H_EFF_G2 = _derive_cofactors()

# RFC 9380 §8.8.2 effective cofactor for the G2 suite.  NOT the exact
# cofactor h2 (= H_EFF_G2): the suite's h_eff is the scalar effected by the
# Budroni-Pintore psi-based fast clearing, s = 4u^2 - 2u - 1 on the G2
# eigencomponent.  Derived, not hardcoded: the unique multiple of h2 that is
# congruent to s mod r with the smallest quotient < r.  Using h2 itself
# would land on [c]P for c = h2*s^-1 != 1 — a valid but non-interoperable
# point (signatures would differ from blst byte-for-byte).
_S_BP = 4 * X_PARAM * X_PARAM - 2 * X_PARAM - 1
H_EFF_G2_RFC = H_EFF_G2 * ((_S_BP * pow(H_EFF_G2, -1, R)) % R)
assert H_EFF_G2_RFC % H_EFF_G2 == 0 and H_EFF_G2_RFC % R == _S_BP % R

assert G1_GENERATOR.is_on_curve()
assert G2_GENERATOR.is_on_curve()


def g1_mul(k: int) -> Point:
    return G1_GENERATOR.mul(k)


def g2_mul(k: int) -> Point:
    return G2_GENERATOR.mul(k)
