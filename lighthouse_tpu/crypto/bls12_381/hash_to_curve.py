"""hash-to-curve for G2: the BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_
ciphersuite (RFC 9380 §8.8.2), matching the reference's blst DST + map
(ref: crypto/bls/src/impls/blst.rs:15, sign :187-220).

- ``expand_message_xmd`` (SHA-256) and ``hash_to_field`` over Fp2 follow
  RFC 9380 §5 exactly.
- ``map_to_curve`` is simplified SWU (§6.6.2) onto the 3-isogenous curve
  E': y^2 = x^3 + 240i*x + 1012(1+i) with Z = -(2+i), followed by the
  3-isogeny to E.  The isogeny's rational-map constants are DERIVED at
  import time via Vélu's formulas from the kernel x0 = -6+6i (the unique
  small-form root of E's 3rd division polynomial) composed with the
  curve isomorphism (x,y) -> (x/9, -y/27); the derivation reproduces the
  RFC 9380 appendix E.3 constants bit-exactly (pinned in
  tests/test_bls12_381.py), so outputs are byte-compatible with blst.

Round 1's SVDW deviation is gone; every hash path is the spec ciphersuite.
"""
from __future__ import annotations

import hashlib
import struct

from .curve import H_EFF_G2_RFC, Point, G2Point, B_G2
from .fields import Fp, Fp2, P

DST_POP = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_L = 64  # ceil((ceil(log2(p)) + k) / 8) = ceil((381 + 128)/8)
_B_IN_BYTES = 32
_R_IN_BYTES = 64


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = struct.pack(">H", len_in_bytes)
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    for i in range(2, ell + 1):
        prev = out[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        out.append(hashlib.sha256(xored + bytes([i]) + dst_prime).digest())
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes) -> list[Fp2]:
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            off = _L * (j + i * 2)
            coeffs.append(Fp(int.from_bytes(uniform[off:off + _L], "big")))
        out.append(Fp2(coeffs[0], coeffs[1]))
    return out


# -- simplified SWU on E' + 3-isogeny to E (RFC 9380 §6.6.2, §8.8.2) ---------

# E': y^2 = x^3 + A'x + B'
ISO_A = Fp2(0, 240)
ISO_B = Fp2(1012, 1012)
SSWU_Z = Fp2(-2 % P, -1 % P)          # Z = -(2 + i)


def map_to_curve_sswu_prime(u: Fp2) -> tuple[Fp2, Fp2]:
    """Simplified SWU onto E' (not E!); compose with iso_map_g2."""
    zu2 = SSWU_Z * u.square()
    tv1 = zu2.square() + zu2
    if tv1.is_zero():
        x1 = ISO_B * (SSWU_Z * ISO_A).inv()
    else:
        x1 = (-ISO_B) * ISO_A.inv() * (Fp2(1, 0) + tv1.inv())
    gx1 = x1 * x1 * x1 + ISO_A * x1 + ISO_B
    if gx1.is_square():
        x, y = x1, gx1.sqrt()
    else:
        x2 = zu2 * x1
        gx2 = x2 * x2 * x2 + ISO_A * x2 + ISO_B
        x, y = x2, gx2.sqrt()
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def _derive_iso_constants():
    """Vélu's formulas for the 3-isogeny E' -> E with kernel x0 = -6+6i,
    composed with (x,y) -> (x/9, -y/27) (the RFC's orientation).  Returns
    (x_num, x_den, y_num, y_den) coefficient lists, low degree first;
    denominators monic with the leading 1 omitted (RFC E.3 layout)."""
    x0 = Fp2(-6 % P, 6)
    assert (x0.square().square() * 3 + x0.square() * (ISO_A * 6)
            + x0 * (ISO_B * 12) - ISO_A.square()).is_zero(), \
        "x0 must be a root of the 3rd division polynomial"
    gx0 = x0 * x0 * x0 + ISO_A * x0 + ISO_B          # y0^2
    t1 = (x0.square() * 3 + ISO_A) * 2               # Σ_kernel t_Q
    u = gx0 * 4                                      # Σ_kernel 2 y_Q^2
    w = (gx0 * 2 + x0 * (x0.square() * 3 + ISO_A)) * 2
    # image curve must be 3^6-isomorphic to E: (0, 2916(1+i)) -> c = 1/3
    assert (ISO_A - t1 * 5).is_zero() and \
        (ISO_B - w * 7) == Fp2(4 * 729, 4 * 729)
    inv9 = Fp2(pow(9, P - 2, P), 0)
    inv27 = Fp2(pow(27, P - 2, P), 0)
    x_num = [(u - t1 * x0) * inv9, (x0.square() + t1) * inv9,
             (-x0 * 2) * inv9, inv9]
    x_den = [x0.square(), -x0 * 2]                   # + x^2
    y_num = [-((-(x0 * x0 * x0) + t1 * x0 - u * 2) * inv27),
             -((x0.square() * 3 - t1) * inv27),
             -((-x0 * 3) * inv27), -inv27]
    y_den = [-(x0 * x0 * x0), x0.square() * 3, -x0 * 3]  # + x^3
    return x_num, x_den, y_num, y_den


ISO_X_NUM, ISO_X_DEN, ISO_Y_NUM, ISO_Y_DEN = _derive_iso_constants()


def _horner(coeffs: list[Fp2], x: Fp2, monic: bool) -> Fp2:
    acc = Fp2(1, 0) if monic else coeffs[-1]
    start = len(coeffs) - 1 if monic else len(coeffs) - 2
    for i in range(start, -1, -1):
        acc = acc * x + coeffs[i]
    return acc


def iso_map_g2(x: Fp2, y: Fp2) -> tuple[Fp2, Fp2] | None:
    """The 3-isogeny E' -> E as rational maps (RFC 9380 appendix E.3).
    Returns None (the point at infinity) on the exceptional kernel inputs
    where a denominator vanishes (RFC 9380 §4.1 inv0 convention)."""
    xn = _horner(ISO_X_NUM, x, monic=False)
    xd = _horner(ISO_X_DEN, x, monic=True)
    yn = _horner(ISO_Y_NUM, x, monic=False)
    yd = _horner(ISO_Y_DEN, x, monic=True)
    if xd.is_zero() or yd.is_zero():
        return None
    return xn * xd.inv(), y * yn * yd.inv()


def map_to_curve_sswu(u: Fp2) -> Point:
    affine = iso_map_g2(*map_to_curve_sswu_prime(u))
    if affine is None:
        return Point.infinity(B_G2)
    return G2Point(*affine)


def clear_cofactor_g2(p: Point) -> Point:
    return p.mul(H_EFF_G2_RFC)


def hash_to_g2(msg: bytes, dst: bytes = DST_POP) -> Point:
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = map_to_curve_sswu(u0)
    q1 = map_to_curve_sswu(u1)
    return clear_cofactor_g2(q0.add(q1))
