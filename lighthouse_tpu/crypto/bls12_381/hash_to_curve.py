"""hash-to-curve for G2 per RFC 9380 structure.

- ``expand_message_xmd`` (SHA-256) and ``hash_to_field`` over Fp2 follow the
  RFC exactly.
- ``map_to_curve`` uses the Shallue–van de Woestijne map (RFC 9380 §6.6.1)
  with constants *derived at import time* from the curve (find_z_svdw,
  appendix H.1) — fully self-validating with zero hardcoded magic.

NOTE (documented deviation): the Ethereum ciphersuite
BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_ uses simplified-SWU on a 3-isogenous
curve. Signer and verifier here share this SVDW map, so all internal
sign/verify/aggregate/batch paths are sound and uniform; swapping in the SSWU
isogeny constants (a Vélu derivation, planned) only changes which G2 point a
message maps to. Cross-client signature interop requires that swap.
"""
from __future__ import annotations

import hashlib
import struct

from .curve import H_EFF_G2, Point, G2Point, B_G2
from .fields import Fp, Fp2, P

DST_POP = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_L = 64  # ceil((ceil(log2(p)) + k) / 8) = ceil((381 + 128)/8)
_B_IN_BYTES = 32
_R_IN_BYTES = 64


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = struct.pack(">H", len_in_bytes)
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    for i in range(2, ell + 1):
        prev = out[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        out.append(hashlib.sha256(xored + bytes([i]) + dst_prime).digest())
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes) -> list[Fp2]:
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            off = _L * (j + i * 2)
            coeffs.append(Fp(int.from_bytes(uniform[off:off + _L], "big")))
        out.append(Fp2(coeffs[0], coeffs[1]))
    return out


# -- SVDW constant derivation (RFC 9380 appendix H.1 / §6.6.1) ---------------

def _g(x: Fp2) -> Fp2:
    return x * x * x + B_G2


def _find_z_svdw() -> Fp2:
    # candidate order: F(ctr), F(-ctr), F(ctr*u), F(-ctr*u), ...
    ctr = 1
    while True:
        for z in (Fp2(ctr, 0), Fp2(-ctr % P, 0), Fp2(0, ctr),
                  Fp2(0, -ctr % P)):
            gz = _g(z)
            if gz.is_zero():
                continue
            h = -(z.square() * 3) * (gz * 4).inv()  # A = 0
            if h.is_zero():
                continue
            if not h.is_square():
                continue
            if gz.is_square() or _g(-z * Fp2(pow(2, P - 2, P), 0)).is_square():
                return z
        ctr += 1


_Z = _find_z_svdw()
_C1 = _g(_Z)                                  # g(Z)
_C2 = -_Z * Fp2(pow(2, P - 2, P), 0)          # -Z / 2
_tmp = -(_C1 * (_Z.square() * 3))             # -g(Z) * (3Z^2 + 4A), A = 0
_C3 = _tmp.sqrt()
assert _C3 is not None
if _C3.sgn0() == 1:
    _C3 = -_C3
_C4 = -(_C1 * 4) * (_Z.square() * 3).inv()    # -4 g(Z) / (3Z^2 + 4A)


def map_to_curve_svdw(u: Fp2) -> tuple[Fp2, Fp2]:
    tv1 = u.square() * _C1
    tv2 = Fp2(1, 0) + tv1
    tv1 = Fp2(1, 0) - tv1
    tv3 = tv1 * tv2
    tv3 = tv3.inv() if not tv3.is_zero() else Fp2(0, 0)
    tv4 = u * tv1 * tv3 * _C3
    x1 = _C2 - tv4
    gx1 = _g(x1)
    e1 = gx1.is_square()
    x2 = _C2 + tv4
    gx2 = _g(x2)
    e2 = gx2.is_square() and not e1
    x3 = tv2.square() * tv3
    x3 = x3.square() * _C4 + _Z
    x = x3
    if e1:
        x = x1
    elif e2:
        x = x2
    gx = _g(x)
    y = gx.sqrt()
    assert y is not None, "map_to_curve: g(x) must be square"
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def clear_cofactor_g2(p: Point) -> Point:
    return p.mul(H_EFF_G2)


def hash_to_g2(msg: bytes, dst: bytes = DST_POP) -> Point:
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = G2Point(*map_to_curve_svdw(u0))
    q1 = G2Point(*map_to_curve_svdw(u1))
    return clear_cofactor_g2(q0.add(q1))
