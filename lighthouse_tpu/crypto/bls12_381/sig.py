"""BLS signatures (min-pubkey-size: pk in G1/48B, sig in G2/96B) +
ZCash-format point compression + random-linear-combination batch verify.

Mirrors the reference's hot function `verify_signature_sets`
(crypto/bls/src/impls/blst.rs:37-119): draw 64-bit random scalars (first set
scalar may be 1), scale (pk_i, sig_i) by r_i, aggregate scaled signatures,
then one multi-pairing:  prod_i e(r_i·pk_i, H(m_i)) · e(-g1, sum r_i·sig_i) == 1.
"""
from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

from .curve import (
    B_G1, B_G2, G1_GENERATOR, G2_GENERATOR, Point,
)
from .fields import Fp, Fp2, P, R
from .hash_to_curve import DST_POP, hash_to_g2
from .pairing import multi_pairing

RAND_BITS = 64  # crypto/bls/src/impls/blst.rs:16


def keygen_interop(index: int) -> int:
    """Deterministic interop secret keys (common/eth2_interop_keypairs)."""
    h = hashlib.sha256(index.to_bytes(32, "little")).digest()
    return int.from_bytes(h, "little") % R


def sk_to_pk(sk: int) -> Point:
    return G1_GENERATOR.mul(sk)


def sign(sk: int, msg: bytes, dst: bytes = DST_POP) -> Point:
    return hash_to_g2(msg, dst).mul(sk)


def verify(pk: Point, msg: bytes, sig: Point, dst: bytes = DST_POP) -> bool:
    if sig.is_infinity() or pk.is_infinity():
        return False
    if not (sig.is_on_curve() and sig.in_subgroup()):
        return False
    h = hash_to_g2(msg, dst)
    return multi_pairing([(G1_GENERATOR.neg(), sig), (pk, h)]).is_one()


def aggregate_signatures(sigs: list[Point]) -> Point:
    out = Point.infinity(B_G2)
    for s in sigs:
        out = out.add(s)
    return out


def aggregate_pubkeys(pks: list[Point]) -> Point:
    out = Point.infinity(B_G1)
    for p in pks:
        out = out.add(p)
    return out


def fast_aggregate_verify(pks: list[Point], msg: bytes, sig: Point,
                          dst: bytes = DST_POP) -> bool:
    """All pubkeys signed the same message."""
    if not pks:
        return False
    return verify(aggregate_pubkeys(pks), msg, sig, dst)


def aggregate_verify(pks: list[Point], msgs: list[bytes], sig: Point,
                     dst: bytes = DST_POP) -> bool:
    """pk_i signed msg_i; one aggregate signature."""
    if not pks or len(pks) != len(msgs):
        return False
    if sig.is_infinity():
        return False
    if not (sig.is_on_curve() and sig.in_subgroup()):
        return False
    pairs = [(G1_GENERATOR.neg(), sig)]
    for pk, m in zip(pks, msgs):
        pairs.append((pk, hash_to_g2(m, dst)))
    return multi_pairing(pairs).is_one()


@dataclass
class SignatureSet:
    """One verification unit: sig over msg by (possibly aggregated) pubkeys."""
    signature: Point
    pubkeys: list[Point]            # aggregated before pairing
    message: bytes                  # 32-byte signing root


def verify_signature_sets_rlc(sets: list[SignatureSet],
                              dst: bytes = DST_POP,
                              rand_fn=None) -> bool:
    """Batched verify via random linear combination + one multi-pairing."""
    if not sets:
        return False
    rand_fn = rand_fn or (lambda: secrets.randbits(RAND_BITS) | 1)
    agg_sig = Point.infinity(B_G2)
    pairs: list[tuple[Point, Point]] = []
    for s in sets:
        if s.signature.is_infinity() or not s.pubkeys:
            return False
        if not (s.signature.is_on_curve() and s.signature.in_subgroup()):
            return False
        r = 1 if len(sets) == 1 else rand_fn()
        pk = aggregate_pubkeys(s.pubkeys)
        if pk.is_infinity():
            return False
        agg_sig = agg_sig.add(s.signature.mul(r))
        pairs.append((pk.mul(r), hash_to_g2(s.message, dst)))
    pairs.append((G1_GENERATOR.neg(), agg_sig))
    return multi_pairing(pairs).is_one()


# -- ZCash-format compression ------------------------------------------------

def _fp2_lex_larger(y: Fp2) -> bool:
    if int(y.c1) != 0:
        return int(y.c1) * 2 > P
    return int(y.c0) * 2 > P


def g1_compress(p: Point) -> bytes:
    if p.is_infinity():
        return bytes([0xC0]) + b"\x00" * 47
    x, y = p.to_affine()
    flags = 0x80 | (0x20 if int(y) * 2 > P else 0)
    out = bytearray(int(x).to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_decompress(data: bytes, subgroup_check: bool = True) -> Point | None:
    if len(data) != 48 or not data[0] & 0x80:
        return None
    if data[0] & 0x40:  # infinity
        if data[0] != 0xC0 or any(data[1:]):
            return None
        return Point.infinity(B_G1)
    y_flag = bool(data[0] & 0x20)
    x_int = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x_int >= P:
        return None
    x = Fp(x_int)
    y = (x * x * x + B_G1).sqrt()
    if y is None:
        return None
    if (int(y) * 2 > P) != y_flag:
        y = -y
    pt = Point.from_affine(x, y, B_G1)
    if subgroup_check and not pt.in_subgroup():
        return None
    return pt


def g2_compress(p: Point) -> bytes:
    if p.is_infinity():
        return bytes([0xC0]) + b"\x00" * 95
    x, y = p.to_affine()
    flags = 0x80 | (0x20 if _fp2_lex_larger(y) else 0)
    out = bytearray(int(x.c1).to_bytes(48, "big") +
                    int(x.c0).to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_decompress(data: bytes, subgroup_check: bool = True) -> Point | None:
    if len(data) != 96 or not data[0] & 0x80:
        return None
    if data[0] & 0x40:
        if data[0] != 0xC0 or any(data[1:]):
            return None
        return Point.infinity(B_G2)
    y_flag = bool(data[0] & 0x20)
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        return None
    x = Fp2(x0, x1)
    y = (x * x * x + B_G2).sqrt()
    if y is None:
        return None
    if _fp2_lex_larger(y) != y_flag:
        y = -y
    pt = Point.from_affine(x, y, B_G2)
    if subgroup_check and not pt.in_subgroup():
        return None
    return pt
