"""BLS12-381 field towers: Fp, Fp2 = Fp[u]/(u^2+1),
Fp6 = Fp2[v]/(v^3 - xi) with xi = 1+u, Fp12 = Fp6[w]/(w^2 - v).

Int-backed, operator-overloaded; optimized for clarity not speed (the speed
paths are the C++ host backend and the limb-decomposed TPU kernels in
lighthouse_tpu/ops/bls12_381.py, which are validated against this module).
"""
from __future__ import annotations

# Field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order (scalar field)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative): p, r are polynomials in x
X_PARAM = -0xD201000000010000

assert R == X_PARAM**4 - X_PARAM**2 + 1
assert P == (X_PARAM - 1) ** 2 * (X_PARAM**4 - X_PARAM**2 + 1) // 3 + X_PARAM


class Fp(int):
    """Element of Fp. Immutable int subclass (value already reduced)."""

    def __new__(cls, v: int):
        return super().__new__(cls, v % P)

    def __add__(self, o):
        return Fp(int(self) + int(o))

    __radd__ = __add__

    def __sub__(self, o):
        return Fp(int(self) - int(o))

    def __rsub__(self, o):
        return Fp(int(o) - int(self))

    def __mul__(self, o):
        return Fp(int(self) * int(o))

    __rmul__ = __mul__

    def __neg__(self):
        return Fp(-int(self))

    def inv(self):
        return Fp(pow(int(self), P - 2, P))

    def __truediv__(self, o):
        return self * Fp(int(o)).inv()

    def is_square(self) -> bool:
        return int(self) == 0 or pow(int(self), (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fp | None":
        # p ≡ 3 (mod 4)
        c = Fp(pow(int(self), (P + 1) // 4, P))
        return c if c * c == self else None

    def sgn0(self) -> int:
        return int(self) & 1


class Fp2:
    __slots__ = ("c0", "c1")

    def __init__(self, c0, c1):
        self.c0 = c0 if isinstance(c0, Fp) else Fp(c0)
        self.c1 = c1 if isinstance(c1, Fp) else Fp(c1)

    def __eq__(self, o):
        return isinstance(o, Fp2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((int(self.c0), int(self.c1)))

    def __repr__(self):
        return f"Fp2({hex(self.c0)}, {hex(self.c1)})"

    def __add__(self, o):
        return Fp2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fp2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fp2(-self.c0, -self.c1)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fp2(self.c0 * o, self.c1 * o)
        # Karatsuba: (a0+a1 u)(b0+b1 u), u^2 = -1
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fp2(t0 - t1, t2 - t0 - t1)

    __rmul__ = __mul__

    def square(self):
        # (a0+a1u)^2 = (a0+a1)(a0-a1) + 2a0a1 u
        a, b = self.c0, self.c1
        return Fp2((a + b) * (a - b), (a * b) * 2)

    def conj(self):
        return Fp2(self.c0, -self.c1)

    def norm(self) -> Fp:
        return self.c0 * self.c0 + self.c1 * self.c1

    def inv(self):
        n = self.norm().inv()
        return Fp2(self.c0 * n, -self.c1 * n)

    def __truediv__(self, o):
        return self * o.inv()

    def mul_by_xi(self):
        """Multiply by xi = 1 + u (the Fp6 non-residue)."""
        return Fp2(self.c0 - self.c1, self.c0 + self.c1)

    def pow(self, e: int):
        out, base = FP2_ONE, self
        while e:
            if e & 1:
                out = out * base
            base = base.square()
            e >>= 1
        return out

    def is_zero(self):
        return int(self.c0) == 0 and int(self.c1) == 0

    def is_square(self) -> bool:
        # a square in Fp2 iff norm(a) is a square in Fp (norm = a^(p+1))
        return self.norm().is_square()

    def sqrt(self) -> "Fp2 | None":
        """Complex-method square root for u^2 = -1 towers."""
        if self.is_zero():
            return Fp2(0, 0)
        a0, a1 = self.c0, self.c1
        if int(a1) == 0:
            s = a0.sqrt()
            if s is not None:
                return Fp2(s, 0)
            s = (-a0).sqrt()
            assert s is not None
            return Fp2(0, s)
        alpha = self.norm().sqrt()
        if alpha is None:
            return None
        inv2 = Fp(2).inv()
        delta = (a0 + alpha) * inv2
        if not delta.is_square():
            delta = (a0 - alpha) * inv2
        x0 = delta.sqrt()
        if x0 is None or int(x0) == 0:
            return None
        x1 = a1 * (x0 * 2).inv()
        cand = Fp2(x0, x1)
        return cand if cand.square() == self else None

    def sgn0(self) -> int:
        # RFC 9380: parity of first nonzero coefficient (c0 first)
        if int(self.c0) != 0:
            return self.c0.sgn0()
        return self.c1.sgn0()


FP2_ZERO = Fp2(0, 0)
FP2_ONE = Fp2(1, 0)
XI = Fp2(1, 1)


class Fp6:
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fp2, c1: Fp2, c2: Fp2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __eq__(self, o):
        return (isinstance(o, Fp6) and self.c0 == o.c0 and self.c1 == o.c1
                and self.c2 == o.c2)

    def __add__(self, o):
        return Fp6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fp6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fp6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        if isinstance(o, Fp2):
            return Fp6(self.c0 * o, self.c1 * o, self.c2 * o)
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_xi() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fp6(c0, c1, c2)

    def square(self):
        return self * self

    def mul_by_v(self):
        """Multiply by v: (c0,c1,c2) -> (xi*c2, c0, c1)."""
        return Fp6(self.c2.mul_by_xi(), self.c0, self.c1)

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2).mul_by_xi()
        t1 = a2.square().mul_by_xi() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = (a0 * t0 + (a2 * t1).mul_by_xi() + (a1 * t2).mul_by_xi()).inv()
        return Fp6(t0 * denom, t1 * denom, t2 * denom)

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()


FP6_ZERO = Fp6(FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = Fp6(FP2_ONE, FP2_ZERO, FP2_ZERO)


class Fp12:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fp6, c1: Fp6):
        self.c0, self.c1 = c0, c1

    def __eq__(self, o):
        return isinstance(o, Fp12) and self.c0 == o.c0 and self.c1 == o.c1

    @staticmethod
    def one():
        return Fp12(FP6_ONE, FP6_ZERO)

    def __add__(self, o):
        return Fp12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fp12(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o):
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fp12(c0, c1)

    def square(self):
        # complex squaring over Fp6 with w^2 = v
        a0, a1 = self.c0, self.c1
        t = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - t - t.mul_by_v()
        return Fp12(c0, t + t)

    def conj(self):
        """Fp12 conjugation (Frobenius^6): negates the w-odd part."""
        return Fp12(self.c0, -self.c1)

    def inv(self):
        # (a0 + a1 w)^-1 = (a0 - a1 w) / (a0^2 - a1^2 v)
        t = (self.c0 * self.c0 - (self.c1 * self.c1).mul_by_v()).inv()
        return Fp12(self.c0 * t, -(self.c1 * t))

    def pow(self, e: int):
        if e < 0:
            return self.inv().pow(-e)
        out, base = Fp12.one(), self
        while e:
            if e & 1:
                out = out * base
            base = base.square()
            e >>= 1
        return out

    def is_one(self):
        return self == Fp12.one()
