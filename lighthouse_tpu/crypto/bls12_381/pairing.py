"""Optimal ate pairing for BLS12-381 (M-type twist).

Miller loop with projective doubling/addition steps producing sparse Fp12
line evaluations (the mul_by_014 shape), product-of-Miller-loops +
single final exponentiation for batch verification — the same structure
`blst::verify_multiple_aggregate_signatures` uses
(/root/reference/crypto/bls/src/impls/blst.rs:37-119), and the structure the
TPU kernel batches across the VPU.
"""
from __future__ import annotations

from .curve import Point
from .fields import (
    FP2_ONE, FP2_ZERO, Fp, Fp2, Fp6, Fp12, P, R, X_PARAM,
)

_X_ABS = abs(X_PARAM)
_X_BITS = bin(_X_ABS)[2:]


def _sparse_014(c0: Fp2, c1: Fp2, c4: Fp2) -> Fp12:
    return Fp12(Fp6(c0, c1, FP2_ZERO), Fp6(FP2_ZERO, c4, FP2_ZERO))


class _G2Proj:
    """Homogeneous projective G2 point used inside the Miller loop."""

    __slots__ = ("x", "y", "z")

    def __init__(self, x: Fp2, y: Fp2, z: Fp2):
        self.x, self.y, self.z = x, y, z


_TWO_INV = Fp(pow(2, P - 2, P))
_B_TWIST = Fp2(4, 4)


def _doubling_step(r: _G2Proj):
    a = (r.x * r.y) * _TWO_INV
    b = r.y.square()
    c = r.z.square()
    e = _B_TWIST * (c * 3)
    f = e * 3
    g = (b + f) * _TWO_INV
    h = (r.y + r.z).square() - (b + c)
    i = e - b
    j = r.x.square()
    e_sq = e.square()
    r.x = a * (b - f)
    r.y = g.square() - e_sq * 3
    r.z = b * h
    # M-type twist line coefficients
    return (i, j * 3, -h)


def _addition_step(r: _G2Proj, qx: Fp2, qy: Fp2):
    theta = r.y - qy * r.z
    lam = r.x - qx * r.z
    c = theta.square()
    d = lam.square()
    e = lam * d
    f = r.z * c
    g = r.x * d
    h = e + f - g * 2
    r.x = lam * h
    r.y = theta * (g - h) - e * r.y
    r.z = r.z * e
    j = theta * qx - lam * qy
    return (j, -theta, lam)


def _ell(f: Fp12, coeffs, px: Fp, py: Fp) -> Fp12:
    c0, c1, c2 = coeffs
    # M-type: scale c2 by p.y, c1 by p.x; sparse mul_by_014
    c2 = Fp2(c2.c0 * py, c2.c1 * py)
    c1 = Fp2(c1.c0 * px, c1.c1 * px)
    return f * _sparse_014(c0, c1, c2)


def miller_loop(pairs: list[tuple[Point, Point]]) -> Fp12:
    """Product of Miller loops over (G1, G2) affine pairs."""
    prepared = []
    for p1, p2 in pairs:
        if p1.is_infinity() or p2.is_infinity():
            continue
        px, py = p1.to_affine()
        qx, qy = p2.to_affine()
        prepared.append((px, py, qx, qy, _G2Proj(qx, qy, FP2_ONE)))
    f = Fp12.one()
    for bit in _X_BITS[1:]:
        f = f.square()
        for px, py, qx, qy, r in prepared:
            f = _ell(f, _doubling_step(r), px, py)
        if bit == "1":
            for px, py, qx, qy, r in prepared:
                f = _ell(f, _addition_step(r, qx, qy), px, py)
    # x < 0: conjugate (equivalent to inversion up to final exponentiation)
    return f.conj()


_HARD_EXP = (P**4 - P**2 + 1) // R


def final_exponentiation(f: Fp12) -> Fp12:
    # easy part: f^((p^6-1)(p^2+1))
    f = f.conj() * f.inv()
    f = f.pow(P * P) * f
    # hard part (generic exponentiation; the perf backends use the
    # x-based addition chain instead)
    return f.pow(_HARD_EXP)


def pairing(p1: Point, p2: Point) -> Fp12:
    """e(P, Q) with P in G1, Q in G2."""
    return final_exponentiation(miller_loop([(p1, p2)]))


def multi_pairing(pairs: list[tuple[Point, Point]]) -> Fp12:
    """prod_i e(P_i, Q_i) — one shared final exponentiation."""
    return final_exponentiation(miller_loop(pairs))
