"""Pure-Python BLS12-381 reference implementation.

The correctness oracle for the C++ host backend and the JAX/Pallas TPU
kernels (lighthouse_tpu/ops/bls12_381.py). Replaces the reference's
`blst` dependency (crypto/bls/Cargo.toml:19, asm/C) as the *reference*
backend; perf backends live elsewhere.
"""
from .fields import P, R, Fp, Fp2, Fp6, Fp12, FP2_ONE, FP2_ZERO
from .curve import (
    G1Point, G2Point, G1_GENERATOR, G2_GENERATOR, g1_mul, g2_mul,
    H_EFF_G1, H_EFF_G2,
)
from .pairing import pairing, multi_pairing, miller_loop, final_exponentiation
from .hash_to_curve import hash_to_g2, expand_message_xmd, DST_POP
from .sig import (
    sk_to_pk, sign, verify, aggregate_signatures, aggregate_pubkeys,
    fast_aggregate_verify, aggregate_verify, verify_signature_sets_rlc,
    g1_compress, g1_decompress, g2_compress, g2_decompress,
    keygen_interop,
)
