"""EIP-2335 keystores (scrypt + AES-128-CTR).

Equivalent of /root/reference/crypto/eth2_keystore (2.9k LoC): encrypt BLS
secret keys at rest; stdlib hashlib.scrypt + the `cryptography` package's AES
(both baked into the image). EIP-2333 hierarchical derivation lives in
key_derivation.py.
"""
from __future__ import annotations

import hashlib
import os
import uuid

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

from . import bls


def _scrypt(password: bytes, salt: bytes) -> bytes:
    return hashlib.scrypt(password, salt=salt, n=16384, r=8, p=1, dklen=32,
                          maxmem=64 * 1024 * 1024 * 2)


def encrypt_secret(secret: bytes, password: bytes) -> dict:
    """EIP-2335 crypto envelope over raw secret bytes (also the seed
    envelope for EIP-2386 wallets)."""
    salt = os.urandom(32)
    iv = os.urandom(16)
    dk = _scrypt(password, salt)
    cipher = Cipher(algorithms.AES(dk[:16]), modes.CTR(iv))
    enc = cipher.encryptor()
    ciphertext = enc.update(secret) + enc.finalize()
    checksum = hashlib.sha256(dk[16:32] + ciphertext).hexdigest()
    return {
        "kdf": {"function": "scrypt",
                "params": {"dklen": 32, "n": 16384, "p": 1, "r": 8,
                           "salt": salt.hex()},
                "message": ""},
        "checksum": {"function": "sha256", "params": {},
                     "message": checksum},
        "cipher": {"function": "aes-128-ctr",
                   "params": {"iv": iv.hex()},
                   "message": ciphertext.hex()},
    }


def decrypt_secret(crypto: dict, password: bytes) -> bytes:
    if crypto["kdf"]["function"] != "scrypt":
        raise ValueError("unsupported kdf")
    params = crypto["kdf"]["params"]
    dk = hashlib.scrypt(password, salt=bytes.fromhex(params["salt"]),
                        n=params["n"], r=params["r"], p=params["p"],
                        dklen=params["dklen"],
                        maxmem=64 * 1024 * 1024 * 2)
    ciphertext = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + ciphertext).hexdigest()
    if checksum != crypto["checksum"]["message"]:
        raise ValueError("bad password (checksum mismatch)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    cipher = Cipher(algorithms.AES(dk[:16]), modes.CTR(iv))
    dec = cipher.decryptor()
    return dec.update(ciphertext) + dec.finalize()


def create_keystore(sk: int, password: bytes,
                    path: str = "m/12381/3600/0/0/0") -> dict:
    pubkey = bls.sk_to_pk(sk)
    return {
        "crypto": encrypt_secret(sk.to_bytes(32, "big"), password),
        "description": "lighthouse_tpu keystore",
        "pubkey": pubkey.hex(),
        "path": path,
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


def decrypt_keystore(keystore: dict, password: bytes) -> int:
    return int.from_bytes(decrypt_secret(keystore["crypto"], password),
                          "big")
