"""Crypto layer (L0): BLS12-381, KZG, SHA-256, keystores.

Equivalent of /root/reference/crypto/* with the backend-generic design of
crypto/bls/src/lib.rs:86-141: every verification site funnels through
``bls.verify_signature_sets`` so the whole client's signature load hits one
batched choke point — which is exactly what maps onto TPU.
"""
