"""C++ host pairing backend (the blst-equivalent, SURVEY.md §2.6 ★NATIVE).

ctypes wrapper over native/bls12_381.cpp: 6x64 Montgomery Fp, sextic-basis
Fp12, affine multi-Miller with batch inversion, psi-endomorphism subgroup
checks and Budroni-Pintore cofactor clearing (both runtime-verified at
library init against the slow mul-by-r / mul-by-h_eff paths).

Byte-compatible with the Python oracle (crypto/bls12_381) and therefore
with blst: hash_to_g2 is the RFC 9380 8.8.2 ciphersuite incl. the RFC
h_eff, cross-checked byte-exact in tests/test_cpp_backend.py.

Reference parity: crypto/bls/src/impls/blst.rs (DST :15, sign :187-220,
verify_signature_sets :37-119).
"""
from __future__ import annotations

import ctypes as C
import pathlib
import secrets
import subprocess
import time

from . import BlsBackend, SignatureSet

_DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
_RAND_BITS = 64


def _load_lib():
    root = pathlib.Path(__file__).resolve().parents[3]
    so = root / "native" / "libbls12381.so"
    if not so.exists():
        subprocess.run(["sh", str(root / "native" / "build.sh")],
                       check=True, capture_output=True)
    lib = C.CDLL(str(so))
    u32p, u64p = C.POINTER(C.c_uint32), C.POINTER(C.c_uint64)
    lib.bls_selftest.restype = C.c_int
    lib.bls_sk_to_pk.argtypes = [C.c_char_p, C.c_char_p]
    lib.bls_sign.argtypes = [C.c_char_p, C.c_char_p, C.c_size_t,
                             C.c_char_p, C.c_size_t, C.c_char_p]
    lib.bls_hash_to_g2.argtypes = [C.c_char_p, C.c_size_t,
                                   C.c_char_p, C.c_size_t, C.c_char_p]
    lib.bls_hash_to_g2_affine.argtypes = [C.c_char_p, C.c_size_t,
                                          C.c_char_p, C.c_size_t, C.c_char_p]
    lib.bls_verify_signature_sets.restype = C.c_int
    lib.bls_verify_signature_sets.argtypes = [
        C.c_size_t, C.c_char_p, C.c_char_p, u32p,
        C.c_char_p, u32p, C.c_char_p, C.c_size_t, u64p]
    lib.bls_aggregate_verify.restype = C.c_int
    lib.bls_aggregate_verify.argtypes = [
        C.c_size_t, C.c_char_p, C.c_char_p, u32p, C.c_char_p,
        C.c_char_p, C.c_size_t]
    lib.bls_aggregate_sigs.restype = C.c_int
    lib.bls_aggregate_sigs.argtypes = [C.c_size_t, C.c_char_p, C.c_char_p]
    lib.bls_aggregate_pks.restype = C.c_int
    lib.bls_aggregate_pks.argtypes = [C.c_size_t, C.c_char_p, C.c_char_p]
    lib.bls_validate_pubkey.restype = C.c_int
    lib.bls_validate_pubkey.argtypes = [C.c_char_p]
    try:  # KZG surface (crypto/kzg.py host acceleration)
        lib.kzg_g1_msm.restype = C.c_int
        lib.kzg_g1_msm.argtypes = [C.c_size_t, C.c_char_p, C.c_char_p,
                                   C.c_char_p]
        lib.kzg_pairing_check.restype = C.c_int
        lib.kzg_pairing_check.argtypes = [C.c_size_t, C.c_char_p, C.c_char_p]
        lib.kzg_g1_mul.restype = C.c_int
        lib.kzg_g1_mul.argtypes = [C.c_char_p, C.c_char_p, C.c_char_p]
    except AttributeError:
        pass  # stale .so predating the KZG exports; kzg.py falls back
    rc = lib.bls_selftest()
    if rc != 0:
        raise RuntimeError(f"bls12_381 native selftest failed: {rc}")
    return lib


_lib = None


def get_lib():
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


class CppBackend(BlsBackend):
    name = "cpp"

    def __init__(self):
        self.lib = get_lib()

    def sk_to_pk(self, sk: int) -> bytes:
        out = C.create_string_buffer(48)
        self.lib.bls_sk_to_pk(sk.to_bytes(32, "big"), out)
        return bytes(out.raw)

    def sign(self, sk: int, msg: bytes) -> bytes:
        out = C.create_string_buffer(96)
        self.lib.bls_sign(sk.to_bytes(32, "big"), msg, len(msg),
                          _DST, len(_DST), out)
        return bytes(out.raw)

    def _verify_sets_raw(self, sets: list[tuple[bytes, list, bytes]],
                         rands: list[int]) -> bool:
        n = len(sets)
        if n == 0:
            return False
        counts = (C.c_uint32 * n)(*[len(s[1]) for s in sets])
        mlens = (C.c_uint32 * n)(*[len(s[2]) for s in sets])
        r = (C.c_uint64 * n)(*rands)
        return self.lib.bls_verify_signature_sets(
            n, b"".join(s[0] for s in sets),
            b"".join(b"".join(s[1]) for s in sets), counts,
            b"".join(s[2] for s in sets), mlens,
            _DST, len(_DST), r) == 1

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return self._verify_sets_raw([(sig, [pk], msg)], [1])

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        if not pks:
            return False
        return self._verify_sets_raw([(sig, list(pks), msg)], [1])

    def aggregate_verify(self, pks, msgs, sig) -> bool:
        if not pks or len(pks) != len(msgs):
            return False
        n = len(pks)
        mlens = (C.c_uint32 * n)(*[len(m) for m in msgs])
        return self.lib.bls_aggregate_verify(
            n, b"".join(pks), b"".join(msgs), mlens, sig,
            _DST, len(_DST)) == 1

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        if not sets:
            return False
        rands = ([1] if len(sets) == 1 else
                 [secrets.randbits(_RAND_BITS) | 1 for _ in sets])
        return self._verify_sets_raw(
            [(s.signature, list(s.pubkeys), s.message) for s in sets], rands)

    def aggregate_signatures(self, sigs) -> bytes:
        out = C.create_string_buffer(96)
        if self.lib.bls_aggregate_sigs(len(sigs), b"".join(sigs), out):
            raise ValueError("invalid signature bytes")
        return bytes(out.raw)

    def aggregate_public_keys(self, pks) -> bytes:
        out = C.create_string_buffer(48)
        if self.lib.bls_aggregate_pks(len(pks), b"".join(pks), out):
            raise ValueError("invalid pubkey bytes")
        return bytes(out.raw)

    def validate_pubkey(self, pk: bytes) -> bool:
        return self.lib.bls_validate_pubkey(pk) == 1


def hash_to_g2_affine(msg: bytes, dst: bytes = _DST) -> tuple:
    """(x.c0, x.c1, y.c0, y.c1) as ints — cross-check helper."""
    out = C.create_string_buffer(192)
    get_lib().bls_hash_to_g2_affine(msg, len(msg), dst, len(dst), out)
    b = bytes(out.raw)
    return tuple(int.from_bytes(b[i * 48:(i + 1) * 48], "big")
                 for i in range(4))


def measure_pairing_throughput(n: int = 64) -> float:
    """Verified signature-sets per second on this host (one process) —
    the bench's measured stand-in for the blst node baseline."""
    b = CppBackend()
    sets = [(b.sign(1000 + i, bytes([i & 0xff, 1]) * 16),
             [b.sk_to_pk(1000 + i)], bytes([i & 0xff, 1]) * 16)
            for i in range(n)]
    rands = [(7 * i + 5) | 1 for i in range(n)]
    assert b._verify_sets_raw(sets, rands)
    t0 = time.perf_counter()
    assert b._verify_sets_raw(sets, rands)
    dt = time.perf_counter() - t0
    return n / dt
