"""TPU BLS backend: `verify_signature_sets` on the device kernels.

The `tpu` entry in the backend registry (--crypto-backend=tpu), mirroring how
the reference selects `blst` (crypto/bls/src/lib.rs:86-141). Pipeline for a
batch of sets:

  host:   parse+range-check compressed bytes, aggregate cached pubkeys,
          expand_message_xmd (a few SHA-256 calls per message)
  device: batched G2 signature decompression (sqrt + sign select), psi
          subgroup checks, SSWU+isogeny+cofactor hash-to-G2, RLC 64-bit
          scalar muls, signature tree-aggregation, n+1 Miller loops, ONE
          final exponentiation.

STATIC SHAPES (round 4, VERDICT r3 "next" #1a): every device stage runs at
one of TWO fixed lane counts per platform (`lane_options()`):

  - big   = the flagship batch (10240 on accelerators — BASELINE.md's 10k
            gossip batch padded to a multiple of the 128-lane vector
            width; 64 on the XLA CPU fallback; LHTPU_BLS_LANES overrides)
  - small = 128 on accelerators (single gossip attestations / one block's
            sets shouldn't pay a 10240-lane pipeline) — on CPU small==big
            so tests compile exactly one shape set.

Batches pad up to the smallest fitting shape with *generator* lanes (valid
points, so on-curve/subgroup checks stay uniform) whose RLC scalar is 0 and
whose Miller output is masked to the identity; batches larger than `big`
verify in fixed-shape chunks.  All pad-lane device inputs are process
constants (cached at first use — no per-call hashing/encoding of padding).
The whole path is therefore a handful of cached XLA programs — no
per-batch-shape recompiles (the r3 operational risk: ~10 min cold compile
per shape on CPU).

Sign/keygen stay on the Python reference backend (cold path).
"""
from __future__ import annotations

import os
import secrets

import numpy as np

from . import BlsBackend, PythonBackend, SignatureSet

RAND_BITS = 64

_LANES: tuple[int, int] | None = None


def lane_options() -> tuple[int, int]:
    """(small, big) compiled batch shapes for this process."""
    global _LANES
    if _LANES is None:
        def _env_int(name):
            raw = os.environ.get(name)
            if not raw:
                return None
            try:
                return int(raw)
            except ValueError:
                raise ValueError(
                    f"{name} must be an integer lane count, got {raw!r}"
                ) from None
        env = _env_int("LHTPU_BLS_LANES")
        if env is not None:
            big = max(1, env)
        else:
            import jax
            big = 10240 if jax.default_backend() != "cpu" else 64
        senv = _env_int("LHTPU_BLS_SMALL")
        # clamp to [1, big]: small <= 0 would silently disable the
        # small-shape path with a nonsensical compiled shape
        small = min(max(1, senv) if senv is not None else min(128, big), big)
        _LANES = (small, big)
    return _LANES


def static_lanes() -> int:
    """The flagship (big) batch shape (kept for tools/bench)."""
    return lane_options()[1]


class _PadCache:
    """Constant device inputs for padding lanes, built once per lane
    count: generator signature x/flag, generator pubkey limbs, and the
    hash-to-field outputs for the empty padding message."""

    def __init__(self):
        from ...ops import bls12_381 as k
        from ...ops import bigint as bi
        from ..bls12_381 import G1_GENERATOR, g2_compress
        from ..bls12_381.curve import G2_GENERATOR
        from ..bls12_381.hash_to_curve import DST_POP
        cb = g2_compress(G2_GENERATOR)
        c1 = int.from_bytes(bytes([cb[0] & 0x1f]) + cb[1:48], "big")
        c0 = int.from_bytes(cb[48:96], "big")
        self.sig_x = k.fp_encode([c0, c1]).reshape(1, 2, bi.NLIMBS)
        self.flag = bool(cb[0] & 0x20)
        gx, gy = G1_GENERATOR.to_affine()
        self.pk_x = k.fp_encode([int(gx)])
        self.pk_y = k.fp_encode([int(gy)])
        u0, u1 = k.hash_to_field_host([b""], DST_POP)
        self.u0 = u0
        self.u1 = u1

    def tile(self, arr: np.ndarray, pad: int) -> np.ndarray:
        return np.broadcast_to(arr, (pad,) + arr.shape[1:])


_PAD: _PadCache | None = None


def parse_sets(backend, sets):
    """Host parse shared by the single-device and mesh-sharded verifiers:
    per-set pubkey aggregation (cached registry points) + compressed-
    signature x/flag extraction with range checks.  Returns
    (pks, sig_xs, flags, msgs) or None when any set is malformed (the
    batch must verify False, not raise)."""
    from ..bls12_381.fields import P as P_INT
    pks, sig_xs, flags, msgs = [], [], [], []
    try:
        for s in sets:
            if not s.pubkeys:
                return None
            pts = [backend._pk(p) for p in s.pubkeys]
            agg = pts[0]
            for p in pts[1:]:
                agg = agg.add(p)
            if agg.is_infinity():
                return None
            pks.append(agg)
            cb = s.signature
            if len(cb) != 96 or not (cb[0] & 0x80) or (cb[0] & 0x40):
                return None           # malformed or infinity signature
            c1 = int.from_bytes(bytes([cb[0] & 0x1f]) + cb[1:48], "big")
            c0 = int.from_bytes(cb[48:96], "big")
            if c0 >= P_INT or c1 >= P_INT:
                return None
            sig_xs.append((c0, c1))
            flags.append(bool(cb[0] & 0x20))
            msgs.append(s.message)
    except ValueError:
        return None
    return pks, sig_xs, flags, msgs


def host_prepare(pks, sig_xs, sig_flags, msgs, lanes: int, small: int):
    """Pad/group host prep shared by both verifiers: same-message
    grouping (segment layout for `g1_segment_sum`), RLC scalars, and the
    padded device input arrays (cached generator constants on padding
    lanes).  Returns a dict of arrays + layout."""
    import secrets

    from ...ops import bigint as bi
    from ...ops import bls12_381 as k
    from ..bls12_381.hash_to_curve import DST_POP

    global _PAD
    if _PAD is None:
        _PAD = _PadCache()
    m = len(pks)
    pad = lanes - m
    groups: dict[bytes, int] = {}
    gid = [groups.setdefault(msg, len(groups)) for msg in msgs]
    n_groups = len(groups)
    msg_lanes = small if n_groups <= small else lanes
    order = sorted(range(m), key=lambda i: gid[i])
    starts = np.zeros(lanes, dtype=np.int32)
    ends = np.zeros(msg_lanes, dtype=np.int32)
    prev = None
    for pos, i in enumerate(order):
        if gid[i] != prev:
            starts[pos] = 1
            prev = gid[i]
        ends[gid[i]] = pos
    if pad:
        starts[m] = 1                  # padding lanes: one junk segment
    rands = [1] if m == 1 else [secrets.randbits(RAND_BITS) | 1
                                for _ in range(m)]

    sig_x_ints: list[int] = []
    for c0, c1 in sig_xs:
        sig_x_ints += [c0, c1]
    sig_x_real = k.fp_encode(sig_x_ints).reshape(m, 2, bi.NLIMBS)
    cat = np.concatenate
    sig_x = cat([sig_x_real, _PAD.tile(_PAD.sig_x, pad)]) if pad \
        else sig_x_real
    flags = np.asarray(list(sig_flags) + [_PAD.flag] * pad, dtype=bool)
    pkx_l, pky_l = [], []
    for p in (pks[i] for i in order):
        x, y = p.to_affine()
        pkx_l.append(int(x))
        pky_l.append(int(y))
    pk_x_real, pk_y_real = k.fp_encode(pkx_l), k.fp_encode(pky_l)
    pk_x = cat([pk_x_real, _PAD.tile(_PAD.pk_x, pad)]) if pad else pk_x_real
    pk_y = cat([pk_y_real, _PAD.tile(_PAD.pk_y, pad)]) if pad else pk_y_real
    umsgs = [None] * n_groups
    for msg, g in groups.items():
        umsgs[g] = msg
    u0_real, u1_real = k.hash_to_field_host(umsgs, DST_POP)
    upad = msg_lanes - n_groups
    u0 = cat([u0_real, _PAD.tile(_PAD.u0, upad)]) if upad else u0_real
    u1 = cat([u1_real, _PAD.tile(_PAD.u1, upad)]) if upad else u1_real
    mask = np.zeros(msg_lanes + 1, dtype=bool)
    mask[:n_groups] = True
    mask[-1] = True                   # the aggregate/-G1 lane is real
    return {
        "sig_x": sig_x, "flags": flags, "pk_x": pk_x, "pk_y": pk_y,
        "u0": u0, "u1": u1, "starts": starts, "ends": ends, "mask": mask,
        "pk_rands": [rands[i] for i in order] + [0] * pad,
        "sig_rands": list(rands) + [0] * pad,
        "n_groups": n_groups, "msg_lanes": msg_lanes,
    }


class TpuBackend(PythonBackend):
    name = "tpu"

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        if not sets:
            return False
        parsed = parse_sets(self, sets)
        if parsed is None:
            return False
        pks, sig_xs, sig_flags, msgs = parsed
        small, big = lane_options()
        n = len(sets)
        for i in range(0, n, big):
            m = min(big, n - i)
            lanes = small if m <= small else big
            if not self._verify_chunk(pks[i:i + m], sig_xs[i:i + m],
                                      sig_flags[i:i + m],
                                      msgs[i:i + m], lanes):
                return False
        return True

    def _verify_chunk(self, pks, sig_xs, sig_flags, msgs,
                      lanes: int) -> bool:
        """One fixed-shape device pass over m<=lanes real sets, padded to
        `lanes` with cached generator lanes (scalar 0, output masked).

        SAME-MESSAGE AGGREGATION (PERF_MODEL.md §3.1): sets sharing a
        message are folded into one pairing pair via
        Σᵢ rᵢ·e(Pᵢ, H(m)) = e(Σᵢ rᵢPᵢ, H(m)) — a 10k gossip attestation
        batch has ~128 distinct AttestationData messages, so hashing and
        the Miller loop (70% of per-lane cost) run at the SMALL static
        shape when the distinct messages fit (host prep + segment layout
        shared with the mesh-sharded verifier in `host_prepare`)."""
        import jax.numpy as jnp

        from ...ops import bls12_381 as k
        from ...ops import bigint as bi
        from ..bls12_381 import G1_GENERATOR

        prep = host_prepare(pks, sig_xs, sig_flags, msgs, lanes,
                            lane_options()[0])

        # device: signature decompression + subgroup check (generator
        # padding keeps both checks uniformly True on padded lanes)
        sig_x = jnp.asarray(prep["sig_x"])
        sig_y, on_curve = k.g2_decompress_batch(sig_x, prep["flags"])
        if not bool(np.asarray(on_curve).all()):
            return False
        one2 = jnp.asarray(np.broadcast_to(k.FP2_ONE, (lanes, 2, bi.NLIMBS)))
        if not bool(np.asarray(
                k.g2_in_subgroup_batch(sig_x, sig_y, one2)).all()):
            return False

        # device: hash unique messages to G2 (host did expand_message_xmd)
        mx, my, mz = k.hash_to_g2_batch_from_u(prep["u0"], prep["u1"])
        msg_x, msg_y = k.jacobian_to_affine_fp2(mx, my, mz)

        one1 = np.broadcast_to(k.FP_ONE, (lanes, bi.NLIMBS))

        # RLC scaling (padded lanes scale to infinity)
        spx, spy, spz = k.g1_scalar_mul_jit(
            prep["pk_x"], prep["pk_y"], one1,
            k.scalars_to_bits(prep["pk_rands"], RAND_BITS))
        ssx, ssy, ssz = k.g2_scalar_mul_jit(
            sig_x, sig_y, one2,
            k.scalars_to_bits(prep["sig_rands"], RAND_BITS))
        # per-message pubkey sums (segmented log-depth reduction);
        # group g's sum lands in lane g
        gpx, gpy, gpz = k.g1_segment_sum(spx, spy, spz, prep["starts"],
                                         prep["ends"])
        # aggregate scaled signatures (scan reduction, 2 cached programs)
        ax, ay, az = k.g2_sum(ssx, ssy, ssz)

        # affine for the miller loop; non-group lanes come out as junk
        # finite coordinates (z=0 inverts to 0) and are masked below
        apx, apy = k.jacobian_to_affine_fp(gpx, gpy, gpz)
        aax, aay = k.jacobian_to_affine_fp2(ax, ay, az)

        neg_g = G1_GENERATOR.neg().to_affine()
        ngx, ngy = k.fp_encode([int(neg_g[0])]), k.fp_encode([int(neg_g[1])])

        px = jnp.concatenate([apx, jnp.asarray(ngx)], axis=0)
        py = jnp.concatenate([apy, jnp.asarray(ngy)], axis=0)
        qx = jnp.concatenate([msg_x, aax[None]], axis=0)
        qy = jnp.concatenate([msg_y, aay[None]], axis=0)
        return bool(np.asarray(
            k.pairing_check_batch(px, py, qx, qy, mask=prep["mask"])))


def _encode_g1_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(int(x))
        ys.append(int(y))
    return k.fp_encode(xs), k.fp_encode(ys)


def _encode_g2_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(x)
        ys.append(y)
    return k.fp2_encode(xs), k.fp2_encode(ys)
