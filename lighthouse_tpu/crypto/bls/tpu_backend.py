"""TPU BLS backend: `verify_signature_sets` on the device kernels.

The `tpu` entry in the backend registry (--crypto-backend=tpu), mirroring how
the reference selects `blst` (crypto/bls/src/lib.rs:86-141). Pipeline for a
batch of sets:

  host:   decompress pk/sig (cached pk cache), hash_to_g2 messages
  device: RLC 64-bit scalar muls (pk_i *= r_i, sig_i *= r_i), signature
          aggregation (tree add), subgroup checks, n+1 Miller loops,
          ONE final exponentiation.

Sign/keygen stay on the Python reference backend (cold path).
"""
from __future__ import annotations

import secrets

import numpy as np

from . import BlsBackend, PythonBackend, SignatureSet

RAND_BITS = 64


class TpuBackend(PythonBackend):
    name = "tpu"

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        import jax.numpy as jnp

        from ...ops import bls12_381 as k
        from ...ops import bigint as bi
        from ..bls12_381 import (
            G1_GENERATOR, R, g2_decompress, hash_to_g2,
        )
        if not sets:
            return False
        try:
            pks = []
            sigs = []
            msgs = []
            for s in sets:
                if not s.pubkeys:
                    return False
                pk_pts = [self._pk(p) for p in s.pubkeys]
                agg = pk_pts[0]
                for p in pk_pts[1:]:
                    agg = agg.add(p)
                if agg.is_infinity():
                    return False
                pks.append(agg)
                sig = g2_decompress(s.signature, subgroup_check=False)
                if sig is None or sig.is_infinity():
                    return False
                sigs.append(sig)
                msgs.append(hash_to_g2(s.message))
        except ValueError:
            return False

        n = len(sets)
        rands = [1 if n == 1 else secrets.randbits(RAND_BITS) | 1
                 for _ in range(n)]

        # encode to device
        pk_x, pk_y = _encode_g1_batch(k, pks)
        sig_x, sig_y = _encode_g2_batch(k, sigs)
        msg_x, msg_y = _encode_g2_batch(k, msgs)

        one1 = np.broadcast_to(k.FP_ONE, (n, bi.NLIMBS))
        one2 = np.broadcast_to(k.FP2_ONE, (n, 2, bi.NLIMBS))
        bits = k.scalars_to_bits(rands, RAND_BITS)

        # subgroup check: r * sig == infinity
        r_bits = k.scalars_to_bits([R] * n, R.bit_length())
        cx, cy, cz = k.g2_scalar_mul(sig_x, sig_y, one2, r_bits)
        if not bool(np.asarray(k.fp2_is_zero(cz)).all()):
            return False

        # RLC scaling
        spx, spy, spz = k.g1_scalar_mul(pk_x, pk_y, one1, bits)
        ssx, ssy, ssz = k.g2_scalar_mul(sig_x, sig_y, one2, bits)
        # aggregate scaled signatures (tree reduction)
        ax, ay, az = _g2_tree_sum(k, ssx, ssy, ssz)

        # affine for the miller loop
        apx, apy = k.jacobian_to_affine_fp(spx, spy, spz)
        aax, aay = k.jacobian_to_affine_fp2(ax, ay, az)

        neg_g = G1_GENERATOR.neg().to_affine()
        ngx, ngy = k.fp_encode([int(neg_g[0])]), k.fp_encode([int(neg_g[1])])

        px = jnp.concatenate([apx, jnp.asarray(ngx)], axis=0)
        py = jnp.concatenate([apy, jnp.asarray(ngy)], axis=0)
        qx = jnp.concatenate([msg_x, aax[None]], axis=0)
        qy = jnp.concatenate([msg_y, aay[None]], axis=0)
        return bool(np.asarray(k.pairing_check_batch(px, py, qx, qy)))


def _encode_g1_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(int(x))
        ys.append(int(y))
    return k.fp_encode(xs), k.fp_encode(ys)


def _encode_g2_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(x)
        ys.append(y)
    return k.fp2_encode(xs), k.fp2_encode(ys)


def _g2_tree_sum(k, x, y, z):
    import jax.numpy as jnp
    n = x.shape[0]
    while n > 1:
        if n % 2:
            zero_pt = (jnp.asarray(np.broadcast_to(k.FP2_ONE,
                                                   (1,) + x.shape[1:])),
                       jnp.asarray(np.broadcast_to(k.FP2_ONE,
                                                   (1,) + y.shape[1:])),
                       jnp.zeros((1,) + z.shape[1:], dtype=jnp.int32))
            x = jnp.concatenate([x, zero_pt[0]], axis=0)
            y = jnp.concatenate([y, zero_pt[1]], axis=0)
            z = jnp.concatenate([z, zero_pt[2]], axis=0)
            n += 1
        h = n // 2
        x, y, z = k.g2_add(x[:h], y[:h], z[:h], x[h:], y[h:], z[h:])
        n = h
    return x[0], y[0], z[0]
