"""TPU BLS backend: `verify_signature_sets` on the device kernels.

The `tpu` entry in the backend registry (--crypto-backend=tpu), mirroring how
the reference selects `blst` (crypto/bls/src/lib.rs:86-141). Pipeline for a
batch of sets:

  host:   parse+range-check compressed bytes, aggregate cached pubkeys,
          expand_message_xmd (a few SHA-256 calls per message)
  device: batched G2 signature decompression (sqrt + sign select), psi
          subgroup checks, SSWU+isogeny+cofactor hash-to-G2, RLC 64-bit
          scalar muls, signature tree-aggregation, n+1 Miller loops, ONE
          final exponentiation.

Round 1 ran decompression and hash_to_g2 per message in pure Python —
VERDICT flagged that host prep as the 10k-batch bottleneck; it is now a
single host->device transfer of parsed field elements.

Sign/keygen stay on the Python reference backend (cold path).
"""
from __future__ import annotations

import secrets

import numpy as np

from . import BlsBackend, PythonBackend, SignatureSet

RAND_BITS = 64


class TpuBackend(PythonBackend):
    name = "tpu"

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        import jax.numpy as jnp

        from ...ops import bls12_381 as k
        from ...ops import bigint as bi
        from ..bls12_381 import G1_GENERATOR
        from ..bls12_381.fields import P as P_INT
        from ..bls12_381.hash_to_curve import DST_POP
        if not sets:
            return False

        # host: aggregate (cached) pubkeys; parse signature x-coords
        n = len(sets)
        pks = []
        sig_x_ints: list[int] = []
        sig_flags = np.zeros(n, dtype=bool)
        try:
            for i, s in enumerate(sets):
                if not s.pubkeys:
                    return False
                pk_pts = [self._pk(p) for p in s.pubkeys]
                agg = pk_pts[0]
                for p in pk_pts[1:]:
                    agg = agg.add(p)
                if agg.is_infinity():
                    return False
                pks.append(agg)
                cb = s.signature
                if len(cb) != 96 or not (cb[0] & 0x80) or (cb[0] & 0x40):
                    return False          # malformed or infinity signature
                c1 = int.from_bytes(bytes([cb[0] & 0x1f]) + cb[1:48], "big")
                c0 = int.from_bytes(cb[48:96], "big")
                if c0 >= P_INT or c1 >= P_INT:
                    return False
                sig_x_ints += [c0, c1]
                sig_flags[i] = bool(cb[0] & 0x20)
        except ValueError:
            return False

        rands = [1 if n == 1 else secrets.randbits(RAND_BITS) | 1
                 for _ in range(n)]

        # device: signature decompression + subgroup check
        sig_x = jnp.asarray(k.fp_encode(sig_x_ints).reshape(n, 2, bi.NLIMBS))
        sig_y, on_curve = k.g2_decompress_batch(sig_x, sig_flags)
        if not bool(np.asarray(on_curve).all()):
            return False
        one2 = jnp.asarray(np.broadcast_to(k.FP2_ONE, (n, 2, bi.NLIMBS)))
        if not bool(np.asarray(
                k.g2_in_subgroup_batch(sig_x, sig_y, one2)).all()):
            return False

        # device: hash messages to G2 (host does only expand_message_xmd)
        mx, my, mz = k.hash_to_g2_batch([s.message for s in sets], DST_POP)
        msg_x, msg_y = k.jacobian_to_affine_fp2(mx, my, mz)

        pk_x, pk_y = _encode_g1_batch(k, pks)
        one1 = np.broadcast_to(k.FP_ONE, (n, bi.NLIMBS))
        bits = k.scalars_to_bits(rands, RAND_BITS)

        # RLC scaling
        spx, spy, spz = k.g1_scalar_mul_jit(pk_x, pk_y, one1, bits)
        ssx, ssy, ssz = k.g2_scalar_mul_jit(sig_x, sig_y, one2, bits)
        # aggregate scaled signatures (scan reduction, 2 cached programs)
        ax, ay, az = k.g2_sum(ssx, ssy, ssz)

        # affine for the miller loop
        apx, apy = k.jacobian_to_affine_fp(spx, spy, spz)
        aax, aay = k.jacobian_to_affine_fp2(ax, ay, az)

        neg_g = G1_GENERATOR.neg().to_affine()
        ngx, ngy = k.fp_encode([int(neg_g[0])]), k.fp_encode([int(neg_g[1])])

        px = jnp.concatenate([apx, jnp.asarray(ngx)], axis=0)
        py = jnp.concatenate([apy, jnp.asarray(ngy)], axis=0)
        qx = jnp.concatenate([msg_x, aax[None]], axis=0)
        qy = jnp.concatenate([msg_y, aay[None]], axis=0)
        return bool(np.asarray(k.pairing_check_batch(px, py, qx, qy)))


def _encode_g1_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(int(x))
        ys.append(int(y))
    return k.fp_encode(xs), k.fp_encode(ys)


def _encode_g2_batch(k, points):
    xs, ys = [], []
    for p in points:
        x, y = p.to_affine()
        xs.append(x)
        ys.append(y)
    return k.fp2_encode(xs), k.fp2_encode(ys)
