"""Backend-generic BLS interface (wire-format level).

Equivalent of /root/reference/crypto/bls/src/lib.rs:86-141 (`define_mod!`
backend selection): the whole client talks to this module in terms of
compressed bytes (pk 48B, sig 96B) and `SignatureSet`s; the backend — chosen
via ``set_backend`` / ``--crypto-backend`` — decides how
``verify_signature_sets`` actually runs:

- ``python``: pure-Python pairing (reference oracle, crypto/bls12_381/)
- ``fake``:   always-valid (fake_crypto equivalent, impls/fake_crypto.rs)
- ``tpu``:    JAX limb-kernel batch verification (ops/bls12_381.py)
- ``cpp``:    C++ host pairing (native/)
"""
from __future__ import annotations

from dataclasses import dataclass

INFINITY_PUBKEY = bytes([0xC0]) + b"\x00" * 47
INFINITY_SIGNATURE = bytes([0xC0]) + b"\x00" * 95


@dataclass
class SignatureSet:
    """One message, one signature, 1+ pubkeys (pre-aggregation)."""
    signature: bytes
    pubkeys: list
    message: bytes


class BlsBackend:
    name = "abstract"

    def sk_to_pk(self, sk: int) -> bytes:
        raise NotImplementedError

    def sign(self, sk: int, msg: bytes) -> bytes:
        raise NotImplementedError

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def fast_aggregate_verify(self, pks: list, msg: bytes,
                              sig: bytes) -> bool:
        raise NotImplementedError

    def aggregate_verify(self, pks: list, msgs: list, sig: bytes) -> bool:
        raise NotImplementedError

    def verify_signature_sets(self, sets: list[SignatureSet]) -> bool:
        raise NotImplementedError

    def aggregate_signatures(self, sigs: list) -> bytes:
        raise NotImplementedError

    def aggregate_public_keys(self, pks: list) -> bytes:
        raise NotImplementedError

    def validate_pubkey(self, pk: bytes) -> bool:
        raise NotImplementedError


#: first byte of a signature the Fake backend treats as INVALID.  A real
#: compressed G2 point can never lead with 0xff (compression + infinity
#: bits both set with a nonzero body), so adversarial tests can forge
#: "cryptographically bad" signatures that still exercise the full
#: verification pipeline: b"\xff" + 95 arbitrary bytes.
POISON_SIGNATURE_BYTE = 0xFF


class FakeBackend(BlsBackend):
    """Always-valid crypto for tests that exercise everything *but* crypto
    (the reference runs most chain tests this way, impls/fake_crypto.rs).
    One carve-out: signatures leading with POISON_SIGNATURE_BYTE fail, so
    invalid-signature adversarial scenarios keep working without real
    pairings."""

    name = "fake"

    @staticmethod
    def _poisoned(sig: bytes) -> bool:
        return len(sig) > 0 and sig[0] == POISON_SIGNATURE_BYTE

    def sk_to_pk(self, sk: int) -> bytes:
        return bytes([0x80]) + (sk % 2**376).to_bytes(47, "big")

    def sign(self, sk: int, msg: bytes) -> bytes:
        return bytes([0x80]) + (sk % 2**120).to_bytes(15, "big") \
            + msg[:32].ljust(32, b"\0") + b"\x00" * 48

    def verify(self, pk, msg, sig) -> bool:
        return not self._poisoned(sig)

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        return bool(pks) and not self._poisoned(sig)

    def aggregate_verify(self, pks, msgs, sig) -> bool:
        return bool(pks) and not self._poisoned(sig)

    def verify_signature_sets(self, sets) -> bool:
        return all(s.pubkeys and not self._poisoned(s.signature)
                   for s in sets)

    def aggregate_signatures(self, sigs) -> bytes:
        return sigs[0] if sigs else INFINITY_SIGNATURE

    def aggregate_public_keys(self, pks) -> bytes:
        return pks[0] if pks else INFINITY_PUBKEY

    def validate_pubkey(self, pk: bytes) -> bool:
        return len(pk) == 48


class PythonBackend(BlsBackend):
    """Pure-Python pairing backend (the correctness oracle)."""

    name = "python"

    def __init__(self):
        self._pk_cache: dict[bytes, object] = {}

    def _pk(self, pk: bytes):
        from ..bls12_381 import g1_decompress
        pt = self._pk_cache.get(pk)
        if pt is None:
            pt = g1_decompress(pk)
            if pt is None:
                raise ValueError("invalid pubkey")
            self._pk_cache[pk] = pt
        return pt

    def sk_to_pk(self, sk: int) -> bytes:
        from ..bls12_381 import g1_compress, sk_to_pk
        return g1_compress(sk_to_pk(sk))

    def sign(self, sk: int, msg: bytes) -> bytes:
        from ..bls12_381 import g2_compress, sign
        return g2_compress(sign(sk, msg))

    def verify(self, pk, msg, sig) -> bool:
        from ..bls12_381 import g2_decompress, verify
        try:
            p = self._pk(pk)
        except ValueError:
            return False
        s = g2_decompress(sig)
        return s is not None and verify(p, msg, s)

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        from ..bls12_381 import fast_aggregate_verify, g2_decompress
        s = g2_decompress(sig)
        if s is None or not pks:
            return False
        try:
            return fast_aggregate_verify([self._pk(p) for p in pks], msg, s)
        except ValueError:
            return False

    def aggregate_verify(self, pks, msgs, sig) -> bool:
        from ..bls12_381 import aggregate_verify, g2_decompress
        s = g2_decompress(sig)
        if s is None:
            return False
        try:
            return aggregate_verify([self._pk(p) for p in pks], msgs, s)
        except ValueError:
            return False

    def verify_signature_sets(self, sets) -> bool:
        from ..bls12_381 import g2_decompress
        from ..bls12_381.sig import (
            SignatureSet as PySet, verify_signature_sets_rlc,
        )
        py_sets = []
        try:
            for s in sets:
                sig = g2_decompress(s.signature)
                if sig is None:
                    return False
                py_sets.append(
                    PySet(sig, [self._pk(p) for p in s.pubkeys], s.message))
        except ValueError:
            return False
        return verify_signature_sets_rlc(py_sets)

    def aggregate_signatures(self, sigs) -> bytes:
        from ..bls12_381 import g2_compress, g2_decompress
        from ..bls12_381.curve import B_G2, Point
        out = Point.infinity(B_G2)
        for s in sigs:
            pt = g2_decompress(s)
            if pt is None:
                raise ValueError("invalid signature in aggregate")
            out = out.add(pt)
        return g2_compress(out)

    def aggregate_public_keys(self, pks) -> bytes:
        from ..bls12_381 import g1_compress
        from ..bls12_381.curve import B_G1, Point
        out = Point.infinity(B_G1)
        for p in pks:
            out = out.add(self._pk(p))
        return g1_compress(out)

    def validate_pubkey(self, pk: bytes) -> bool:
        # spec KeyValidate: reject the identity point as well as
        # malformed/off-curve encodings
        if pk == b"\xc0" + b"\x00" * 47:
            return False
        try:
            self._pk(pk)
            return True
        except ValueError:
            return False


_BACKENDS: dict[str, BlsBackend] = {}
_current: BlsBackend | None = None


def get_backend() -> BlsBackend:
    """Fail-closed: an entry point that never called set_backend gets real
    (python) crypto, never the always-valid fake backend — 'fake' must be
    an explicit opt-in (--crypto-backend=fake / tests), mirroring the
    reference's fake_crypto feature gate."""
    global _current
    if _current is None:
        _current = _make("python")
    return _current


def _make(name: str) -> BlsBackend:
    if name not in _BACKENDS:
        if name == "fake":
            _BACKENDS[name] = FakeBackend()
        elif name == "python":
            _BACKENDS[name] = PythonBackend()
        elif name == "tpu":
            from .tpu_backend import TpuBackend
            _BACKENDS[name] = TpuBackend()
        elif name == "cpp":
            from .cpp_backend import CppBackend
            _BACKENDS[name] = CppBackend()
        else:
            raise ValueError(f"unknown bls backend {name!r}")
    return _BACKENDS[name]


def set_backend(name: str) -> BlsBackend:
    global _current
    _current = _make(name)
    return _current


# -- module-level convenience (dispatch to current backend) -------------------

def sk_to_pk(sk: int) -> bytes:
    return get_backend().sk_to_pk(sk)


def sign(sk: int, msg: bytes) -> bytes:
    return get_backend().sign(sk, msg)


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    return get_backend().verify(pk, msg, sig)


def fast_aggregate_verify(pks, msg, sig) -> bool:
    return get_backend().fast_aggregate_verify(pks, msg, sig)


def aggregate_verify(pks, msgs, sig) -> bool:
    return get_backend().aggregate_verify(pks, msgs, sig)


def verify_signature_sets(sets: list[SignatureSet]) -> bool:
    # hot-path tracing (beacon_chain/src/metrics.rs style): the span
    # joins whatever trace is active (block import, attestation batch)
    # and feeds the CATALOG histograms — obs stays weightless for
    # library use (its metrics feed is sys.modules-gated)
    from ...obs import tracing
    with tracing.span("bls_batch_verify", sets=len(sets)):
        out = get_backend().verify_signature_sets(sets)
    import sys
    m = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if m is not None:
        m.observe("beacon_batch_verify_signature_sets", len(sets))
        m.observe("bls_batch_verify_sigs", len(sets))
    return out


def aggregate_signatures(sigs) -> bytes:
    return get_backend().aggregate_signatures(sigs)


def aggregate_public_keys(pks) -> bytes:
    return get_backend().aggregate_public_keys(pks)


def keygen_interop(index: int) -> int:
    from ..bls12_381.sig import keygen_interop as _k
    return _k(index)
