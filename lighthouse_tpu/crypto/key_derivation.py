"""EIP-2333 BLS key derivation (crypto/eth2_key_derivation equivalent)."""
from __future__ import annotations

import hashlib
import hmac

from .bls12_381.fields import R


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def _hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    salt = b"BLS-SIG-KEYGEN-SALT-"
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % R
    return sk


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport_0 = _hkdf_expand(_hkdf_extract(salt, ikm), b"", 8160)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _hkdf_expand(_hkdf_extract(salt, not_ikm), b"", 8160)
    combined = b"".join(
        hashlib.sha256(chunk[i * 32:(i + 1) * 32]).digest()
        for chunk in (lamport_0, lamport_1) for i in range(255))
    return hashlib.sha256(combined).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed too short")
    return _hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    return _hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def derive_path(seed: bytes, path: str) -> int:
    """e.g. m/12381/3600/0/0/0 (EIP-2334)."""
    parts = path.split("/")
    if parts[0] != "m":
        raise ValueError("path must start with m")
    sk = derive_master_sk(seed)
    for p in parts[1:]:
        sk = derive_child_sk(sk, int(p))
    return sk
