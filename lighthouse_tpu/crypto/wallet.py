"""EIP-2386 hierarchical deterministic wallet (crypto/eth2_wallet).

A wallet wraps an encrypted EIP-2333 seed plus a `nextaccount` counter;
validator keys derive at the EIP-2334 paths m/12381/3600/{i}/0/0
(voting) and m/12381/3600/{i}/0 (withdrawal).  The seed is encrypted
with the same scrypt+AES-128-CTR construction as EIP-2335 keystores
(crypto/keystore.py), as the reference's `hd` wallet type does
(ref: crypto/eth2_wallet, account_manager/src/wallet).
"""
from __future__ import annotations

import json
import os
import uuid as uuidlib

from .key_derivation import derive_path
from .keystore import create_keystore, decrypt_secret, encrypt_secret


def create_wallet(name: str, password: bytes,
                  seed: bytes | None = None) -> dict:
    """New EIP-2386 wallet JSON (type 'hd')."""
    seed = seed if seed is not None else os.urandom(32)
    crypto = encrypt_secret(seed, password)
    return {
        "crypto": crypto,
        "name": name,
        "nextaccount": 0,
        "type": "hd",
        "uuid": str(uuidlib.uuid4()),
        "version": 1,
    }


def decrypt_seed(wallet: dict, password: bytes) -> bytes:
    return decrypt_secret(wallet["crypto"], password)


class Wallet:
    """Operational wrapper: derive the next validator, produce keystores."""

    def __init__(self, data: dict):
        self.data = data

    @classmethod
    def create(cls, name: str, password: bytes,
               seed: bytes | None = None) -> "Wallet":
        return cls(create_wallet(name, password, seed))

    @classmethod
    def from_json(cls, data: dict) -> "Wallet":
        if data.get("type") != "hd" or data.get("version") != 1:
            raise ValueError("unsupported wallet type/version")
        return cls(data)

    @property
    def name(self) -> str:
        return self.data["name"]

    @property
    def nextaccount(self) -> int:
        return self.data["nextaccount"]

    def derive_validator(self, password: bytes,
                         index: int | None = None) -> tuple[int, int, int]:
        """Returns (account_index, voting_sk, withdrawal_sk); advances
        `nextaccount` when deriving the next sequential account."""
        seed = decrypt_seed(self.data, password)
        i = index if index is not None else self.data["nextaccount"]
        voting = derive_path(seed, f"m/12381/3600/{i}/0/0")
        withdrawal = derive_path(seed, f"m/12381/3600/{i}/0")
        if index is None:
            self.data["nextaccount"] = i + 1
        return i, voting, withdrawal

    def next_validator_keystore(self, wallet_password: bytes,
                                keystore_password: bytes) -> dict:
        """Derive the next account and wrap its voting key in an
        EIP-2335 keystore (the account_manager `validator create` flow)."""
        i, voting, _withdrawal = self.derive_validator(wallet_password)
        ks = create_keystore(voting, keystore_password,
                             path=f"m/12381/3600/{i}/0/0")
        return ks


class WalletManager:
    """Directory-of-wallets CRUD (account_manager/src/wallet)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.base_dir, f"{name}.json")

    def create(self, name: str, password: bytes) -> Wallet:
        if os.path.exists(self._path(name)):
            raise FileExistsError(f"wallet {name!r} exists")
        w = Wallet.create(name, password)
        self.save(w)
        return w

    def open(self, name: str) -> Wallet:
        with open(self._path(name)) as f:
            return Wallet.from_json(json.load(f))

    def save(self, w: Wallet) -> None:
        with open(self._path(w.name), "w") as f:
            json.dump(w.data, f, indent=2)

    def list(self) -> list[str]:
        return sorted(f[:-5] for f in os.listdir(self.base_dir)
                      if f.endswith(".json"))
