"""SQLite slashing protection (EIP-3076 interchange format).

Equivalent of /root/reference/validator_client/slashing_protection: the
authoritative "don't double sign" database — checked on EVERY signature,
transactional, with interchange import/export.
"""
from __future__ import annotations

import json
import sqlite3
import threading


class SlashingError(Exception):
    pass


class SlashingDatabase:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._db.executescript("""
        CREATE TABLE IF NOT EXISTS validators (
            id INTEGER PRIMARY KEY, pubkey BLOB UNIQUE NOT NULL);
        CREATE TABLE IF NOT EXISTS signed_blocks (
            validator_id INTEGER NOT NULL REFERENCES validators(id),
            slot INTEGER NOT NULL, signing_root BLOB,
            UNIQUE (validator_id, slot));
        CREATE TABLE IF NOT EXISTS signed_attestations (
            validator_id INTEGER NOT NULL REFERENCES validators(id),
            source_epoch INTEGER NOT NULL, target_epoch INTEGER NOT NULL,
            signing_root BLOB, UNIQUE (validator_id, target_epoch));
        CREATE TABLE IF NOT EXISTS metadata (
            key TEXT PRIMARY KEY, value TEXT);
        """)
        self._db.commit()

    def register_validator(self, pubkey: bytes) -> int:
        with self._lock:
            cur = self._db.execute(
                "INSERT OR IGNORE INTO validators (pubkey) VALUES (?)",
                (pubkey,))
            self._db.commit()
            row = self._db.execute(
                "SELECT id FROM validators WHERE pubkey = ?",
                (pubkey,)).fetchone()
            return row[0]

    def _vid(self, pubkey: bytes) -> int | None:
        row = self._db.execute("SELECT id FROM validators WHERE pubkey = ?",
                               (pubkey,)).fetchone()
        return row[0] if row else None

    # -- blocks --------------------------------------------------------------

    def check_and_insert_block_proposal(self, pubkey: bytes, slot: int,
                                        signing_root: bytes) -> None:
        with self._lock:
            vid = self._vid(pubkey)
            if vid is None:
                raise SlashingError("unregistered validator")
            row = self._db.execute(
                "SELECT slot, signing_root FROM signed_blocks "
                "WHERE validator_id = ? AND slot = ?",
                (vid, slot)).fetchone()
            if row is not None:
                if row[1] == signing_root:
                    return  # same proposal, safe re-sign
                raise SlashingError(f"double block proposal at slot {slot}")
            low = self._db.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id = ?",
                (vid,)).fetchone()[0]
            if low is not None and slot <= low:
                raise SlashingError(
                    f"block slot {slot} not above previous {low}")
            self._db.execute(
                "INSERT INTO signed_blocks VALUES (?, ?, ?)",
                (vid, slot, signing_root))
            self._db.commit()

    # -- attestations --------------------------------------------------------

    def check_and_insert_attestation(self, pubkey: bytes, source_epoch: int,
                                     target_epoch: int,
                                     signing_root: bytes) -> None:
        if source_epoch > target_epoch:
            raise SlashingError("source after target")
        with self._lock:
            vid = self._vid(pubkey)
            if vid is None:
                raise SlashingError("unregistered validator")
            row = self._db.execute(
                "SELECT source_epoch, signing_root FROM signed_attestations "
                "WHERE validator_id = ? AND target_epoch = ?",
                (vid, target_epoch)).fetchone()
            if row is not None:
                if row[1] == signing_root:
                    return
                raise SlashingError(
                    f"double vote at target epoch {target_epoch}")
            # surround checks
            surrounding = self._db.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id = ? "
                "AND source_epoch < ? AND target_epoch > ?",
                (vid, source_epoch, target_epoch)).fetchone()
            if surrounding:
                raise SlashingError("attestation surrounded by prior vote")
            surrounded = self._db.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id = ? "
                "AND source_epoch > ? AND target_epoch < ?",
                (vid, source_epoch, target_epoch)).fetchone()
            if surrounded:
                raise SlashingError("attestation surrounds prior vote")
            self._db.execute(
                "INSERT INTO signed_attestations VALUES (?, ?, ?, ?)",
                (vid, source_epoch, target_epoch, signing_root))
            self._db.commit()

    # -- EIP-3076 interchange ------------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        out = {"metadata": {
            "interchange_format_version": "5",
            "genesis_validators_root": "0x" + genesis_validators_root.hex()},
            "data": []}
        with self._lock:
            for vid, pk in self._db.execute(
                    "SELECT id, pubkey FROM validators"):
                blocks = [{"slot": str(s),
                           "signing_root": "0x" + (r or b"").hex()}
                          for s, r in self._db.execute(
                              "SELECT slot, signing_root FROM signed_blocks "
                              "WHERE validator_id = ?", (vid,))]
                atts = [{"source_epoch": str(s), "target_epoch": str(t),
                         "signing_root": "0x" + (r or b"").hex()}
                        for s, t, r in self._db.execute(
                            "SELECT source_epoch, target_epoch, signing_root "
                            "FROM signed_attestations WHERE validator_id = ?",
                            (vid,))]
                out["data"].append({"pubkey": "0x" + pk.hex(),
                                    "signed_blocks": blocks,
                                    "signed_attestations": atts})
        return out

    def import_interchange(self, data: dict,
                           genesis_validators_root: bytes) -> None:
        meta_root = bytes.fromhex(
            data["metadata"]["genesis_validators_root"][2:])
        if meta_root != genesis_validators_root:
            raise SlashingError("interchange for a different chain")
        for entry in data["data"]:
            pk = bytes.fromhex(entry["pubkey"][2:])
            self.register_validator(pk)
            for b in entry.get("signed_blocks", []):
                try:
                    self.check_and_insert_block_proposal(
                        pk, int(b["slot"]),
                        bytes.fromhex(b.get("signing_root", "0x")[2:]))
                except SlashingError:
                    pass  # keep the most restrictive record
            for a in entry.get("signed_attestations", []):
                try:
                    self.check_and_insert_attestation(
                        pk, int(a["source_epoch"]), int(a["target_epoch"]),
                        bytes.fromhex(a.get("signing_root", "0x")[2:]))
                except SlashingError:
                    pass
