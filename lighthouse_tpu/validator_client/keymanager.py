"""Keymanager API server (validator_client/http_api in the reference).

Implements the standard keymanager routes against the ValidatorStore +
slashing database:

  GET/POST/DELETE /eth/v1/keystores            (local keys, EIP-2335)
  GET/POST/DELETE /eth/v1/remotekeys           (Web3Signer-backed keys)
  GET/POST/DELETE /eth/v1/validator/{pubkey}/feerecipient
  GET/POST/DELETE /eth/v1/validator/{pubkey}/gas_limit
  POST            /eth/v1/validator/{pubkey}/voluntary_exit
  GET/POST/DELETE /eth/v1/validator/{pubkey}/graffiti

DELETE /eth/v1/keystores returns the EIP-3076 slashing-protection
interchange for the deleted keys, as the spec requires.  Auth: a bearer
token generated at startup (api-token.txt convention).
"""
from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

from ..crypto.keystore import decrypt_keystore


class KeymanagerServer:
    def __init__(self, vc, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None):
        self.vc = vc                       # ValidatorClient
        self.store = vc.store
        self.token = token or secrets.token_hex(16)
        handler = self._make_handler()
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- handlers ------------------------------------------------------------

    def list_keystores(self) -> list[dict]:
        return [{"validating_pubkey": "0x" + pk.hex(),
                 "derivation_path": "", "readonly": False}
                for pk in self.store.voting_pubkeys()
                if pk not in getattr(self.store, "_remote_keys", {})]

    def import_keystores(self, body: dict) -> list[dict]:
        out = []
        for ks_json, password in zip(body.get("keystores", []),
                                     body.get("passwords", [])):
            try:
                ks = (json.loads(ks_json) if isinstance(ks_json, str)
                      else ks_json)
                sk = decrypt_keystore(ks, password.encode()
                                      if isinstance(password, str)
                                      else password)
                self.store.add_validator(sk)
                out.append({"status": "imported"})
            except Exception as e:
                out.append({"status": "error", "message": repr(e)})
        if body.get("slashing_protection"):
            data = body["slashing_protection"]
            self.store.slashing_db.import_interchange(
                json.loads(data) if isinstance(data, str) else data,
                self.store.genesis_validators_root)
        return out

    def delete_keystores(self, pubkeys: list[str]) -> dict:
        statuses = []
        deleted = []
        for pk_hex in pubkeys:
            pk = bytes.fromhex(pk_hex[2:])
            if pk in self.store._keys:
                del self.store._keys[pk]
                deleted.append(pk)
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        interchange = self.store.slashing_db.export_interchange(
            self.store.genesis_validators_root)
        keep = {"0x" + pk.hex() for pk in deleted}
        interchange["data"] = [d for d in interchange.get("data", [])
                               if d.get("pubkey") in keep]
        return {"data": statuses,
                "slashing_protection": json.dumps(interchange)}

    def list_remotekeys(self) -> list[dict]:
        remote = getattr(self.store, "_remote_keys", {})
        return [{"pubkey": "0x" + pk.hex(), "url": url, "readonly": False}
                for pk, url in remote.items()]

    def import_remotekeys(self, body: dict) -> list[dict]:
        out = []
        for rk in body.get("remote_keys", []):
            try:
                pk = bytes.fromhex(rk["pubkey"][2:])
                self.store.add_remote_validator(pk, rk["url"])
                out.append({"status": "imported"})
            except Exception as e:
                out.append({"status": "error", "message": repr(e)})
        return out

    def delete_remotekeys(self, pubkeys: list[str]) -> list[dict]:
        remote = getattr(self.store, "_remote_keys", {})
        out = []
        for pk_hex in pubkeys:
            pk = bytes.fromhex(pk_hex[2:])
            if pk in remote:
                self.store.remove_remote_validator(pk)
                out.append({"status": "deleted"})
            else:
                out.append({"status": "not_found"})
        return out

    def _make_handler(self):
        km = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {km.token}"

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                return json.loads(raw) if raw else {}

            def _route(self, method: str):
                if not self._authed():
                    return self._json(401, {"message": "unauthorized"})
                path = urlparse(self.path).path
                vc = km.vc
                try:
                    if path == "/eth/v1/keystores":
                        if method == "GET":
                            return self._json(200,
                                              {"data": km.list_keystores()})
                        if method == "POST":
                            return self._json(200, {
                                "data": km.import_keystores(self._body())})
                        if method == "DELETE":
                            return self._json(
                                200, km.delete_keystores(
                                    self._body().get("pubkeys", [])))
                    if path == "/eth/v1/remotekeys":
                        if method == "GET":
                            return self._json(200,
                                              {"data": km.list_remotekeys()})
                        if method == "POST":
                            return self._json(200, {
                                "data": km.import_remotekeys(self._body())})
                        if method == "DELETE":
                            return self._json(200, {
                                "data": km.delete_remotekeys(
                                    self._body().get("pubkeys", []))})
                    import re as _re
                    m = _re.match(
                        r"^/eth/v1/validator/(0x[0-9a-fA-F]+)/"
                        r"voluntary_exit$", path)
                    if m and method == "POST":
                        pk = bytes.fromhex(m[1][2:])
                        idx = vc._indices.get(pk)
                        if idx is None:
                            return self._json(
                                400, {"message":
                                      "validator index unknown; wait for "
                                      "duties resolution"})
                        epoch = int(self._body().get("epoch", 0))
                        sve = vc.sign_voluntary_exit(pk, idx, epoch)
                        return self._json(200, {"data": sve})
                    m = _re.match(
                        r"^/eth/v1/validator/(0x[0-9a-fA-F]+)/"
                        r"(feerecipient|gas_limit|graffiti)$", path)
                    if m:
                        pk = bytes.fromhex(m[1][2:])
                        kind = m[2]
                        if kind == "feerecipient":
                            if method == "GET":
                                fee = vc._fee_recipient(pk)
                                if fee is None:
                                    return self._json(404, {
                                        "message": "no fee recipient"})
                                return self._json(200, {"data": {
                                    "pubkey": m[1],
                                    "ethaddress": "0x" + fee.hex()}})
                            if method == "POST":
                                addr = self._body()["ethaddress"]
                                vc.fee_recipients[pk] = \
                                    bytes.fromhex(addr[2:])
                                vc._prepared_epoch = -1  # re-push
                                return self._json(202, {})
                            if method == "DELETE":
                                vc.fee_recipients.pop(pk, None)
                                return self._json(204, {})
                        if kind == "gas_limit":
                            if method == "GET":
                                return self._json(200, {"data": {
                                    "pubkey": m[1],
                                    "gas_limit": str(vc.gas_limit)}})
                            if method == "POST":
                                vc.gas_limit = int(
                                    self._body()["gas_limit"])
                                return self._json(202, {})
                            if method == "DELETE":
                                vc.gas_limit = 30_000_000
                                return self._json(204, {})
                        if kind == "graffiti":
                            g = getattr(vc, "graffiti", {})
                            if method == "GET":
                                return self._json(200, {"data": {
                                    "pubkey": m[1],
                                    "graffiti": g.get(pk, "")}})
                            if method == "POST":
                                vc.graffiti = g
                                g[pk] = self._body()["graffiti"]
                                return self._json(202, {})
                            if method == "DELETE":
                                g.pop(pk, None)
                                return self._json(204, {})
                    return self._json(404, {"message": "route not found"})
                except Exception as e:
                    return self._json(400, {"message": repr(e)})

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_DELETE(self):
                self._route("DELETE")

        return Handler
