"""Typed Beacon-API HTTP client.

Equivalent of /root/reference/common/eth2 (BeaconNodeHttpClient,
src/lib.rs:158): the VC-facing client implementing BeaconNodeInterface over
HTTP, so `ValidatorClient` runs identically in-process or against a remote
beacon node.
"""
from __future__ import annotations

import http.client
import json
import time
from urllib.parse import urlencode, urlparse

from ..containers import get_types
from ..specs.chain_spec import ChainSpec
from ..ssz import deserialize, serialize
from .client import BeaconNodeInterface


class HttpApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        self.status = status
        super().__init__(f"http {status}: {message}")


class BeaconNodeHttpClient(BeaconNodeInterface):
    def __init__(self, url: str, spec: ChainSpec, timeout: float = 10.0,
                 retries: int = 2, backoff: float = 0.1):
        p = urlparse(url)
        self.host = p.hostname or "127.0.0.1"
        self.port = p.port or 5052
        self.timeout = timeout
        self.retries = retries          # extra attempts after the first
        self.backoff = backoff          # base delay, doubled per attempt
        self.retry_count = 0
        self.spec = spec
        self.T = get_types(spec.preset)

    def _req(self, method: str, path: str, body: bytes | None = None,
             json_body=None, raw: bool = False):
        """One request with bounded connection-level retries.  Only
        transport failures (refused/reset/timeout — OSError family) are
        retried: an HTTP status >= 400 means the BN heard us and said no,
        and blindly re-POSTing a block or attestation would not change
        its mind (the eth2 client's no-retry-on-4xx discipline)."""
        headers = {}
        if raw:
            # SSZ responses are opt-in since round 4 (the server
            # negotiates JSON by default, per the Beacon API spec)
            headers["Accept"] = "application/octet-stream"
        if json_body is not None:
            body = json.dumps(json_body).encode()
            headers["Content-Type"] = "application/json"
        elif body is not None:
            headers["Content-Type"] = "application/octet-stream"
        last_err: Exception | None = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.retry_count += 1
                from ..api import metrics_defs as M
                M.count("vc_http_retries_total")
                time.sleep(self.backoff * (2 ** (attempt - 1)))
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
                r = conn.getresponse()
                data = r.read()
                if r.status >= 400:
                    raise HttpApiError(r.status,
                                       data[:200].decode("latin1"))
                return data if raw else (json.loads(data) if data else {})
            except (OSError, TimeoutError, http.client.HTTPException) as e:
                last_err = e
            finally:
                conn.close()
        assert last_err is not None
        raise last_err

    # -- BeaconNodeInterface -------------------------------------------------

    def is_healthy(self) -> bool:
        try:
            self._req("GET", "/eth/v1/node/health")
            return True
        except (HttpApiError, OSError):
            return False

    def get_proposer_duties(self, epoch: int):
        out = self._req("GET", f"/eth/v1/validator/duties/proposer/{epoch}")
        return [(int(d["slot"]), int(d["validator_index"]))
                for d in out["data"]]

    def get_attester_duties(self, epoch: int, indices: list[int]):
        out = self._req("POST", f"/eth/v1/validator/duties/attester/{epoch}",
                        json_body=[str(i) for i in indices])
        return [(int(d["slot"]), int(d["committee_index"]),
                 int(d["validator_index"]), int(d["committee_length"]),
                 int(d["validator_committee_index"])) for d in out["data"]]

    def get_validator_index(self, pubkey: bytes):
        out = self._req("GET", "/eth/v1/validator/validator_index?"
                        + urlencode({"pubkey": "0x" + pubkey.hex()}))
        idx = out["data"]["index"]
        return int(idx) if idx is not None else None

    def produce_block(self, slot: int, randao_reveal: bytes,
                      graffiti: bytes | None = None):
        params = {"randao_reveal": "0x" + randao_reveal.hex()}
        if graffiti:
            params["graffiti"] = "0x" + graffiti.hex()
        raw = self._req("GET", f"/eth/v2/validator/blocks/{slot}?"
                        + urlencode(params), raw=True)
        fork = self.spec.fork_name_at_slot(slot)
        return deserialize(self.T.BeaconBlock[fork].ssz_type, raw)

    def publish_block(self, signed_block) -> None:
        self._req("POST", "/eth/v1/beacon/blocks",
                  body=serialize(type(signed_block).ssz_type, signed_block))

    def attestation_data(self, slot: int, committee_index: int):
        out = self._req("GET", "/eth/v1/validator/attestation_data?"
                        + urlencode({"slot": slot,
                                     "committee_index": committee_index}))
        return deserialize(self.T.AttestationData.ssz_type,
                           bytes.fromhex(out["data"]["ssz"]))

    def publish_attestation(self, attestation) -> None:
        self._req("POST", "/eth/v1/beacon/pool/attestations",
                  body=serialize(type(attestation).ssz_type, attestation))

    def get_aggregate(self, slot: int, committee_index: int):
        try:
            out = self._req("GET", "/eth/v1/validator/aggregate_attestation?"
                            + urlencode({"slot": slot,
                                         "committee_index": committee_index}))
        except HttpApiError as e:
            if e.status == 404:
                return None
            raise
        return deserialize(self.T.Attestation.ssz_type,
                           bytes.fromhex(out["data"]["ssz"]))

    def publish_aggregate(self, signed_aggregate) -> None:
        self._req("POST", "/eth/v1/validator/aggregate_and_proofs",
                  body=serialize(type(signed_aggregate).ssz_type,
                                 signed_aggregate))

    def head_fork_version(self) -> bytes:
        out = self._req("GET", "/eth/v1/validator/fork_version")
        return bytes.fromhex(out["data"]["version"][2:])

    def get_sync_duties(self, epoch: int, indices: list[int]) -> list[int]:
        qs = "&".join(f"id={i}" for i in indices)
        out = self._req("GET", f"/eth/v1/validator/sync_duties/{epoch}?{qs}")
        return [int(i) for i in out["data"]]

    def head_root(self) -> bytes:
        out = self._req("GET", "/lighthouse/head_root")
        return bytes.fromhex(out["data"]["root"][2:])

    def publish_sync_committee_message(self, msg) -> None:
        self._req("POST", "/eth/v1/beacon/pool/sync_committees",
                  body=serialize(type(msg).ssz_type, msg))

    def seen_liveness(self, indices: list[int], epoch: int):
        qs = "&".join(f"id={i}" for i in indices)
        out = self._req("GET", f"/eth/v1/validator/liveness/{epoch}?{qs}")
        return out["data"]

    def prepare_beacon_proposer(self, entries: list[dict]) -> None:
        self._req("POST", "/eth/v1/validator/prepare_beacon_proposer",
                  json_body=entries)

    def register_validator(self, registrations: list[dict]) -> None:
        self._req("POST", "/eth/v1/validator/register_validator",
                  json_body=registrations)
