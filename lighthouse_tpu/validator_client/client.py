"""The validator client service loop.

Equivalent of /root/reference/validator_client/src/lib.rs:552-645 service
spawn: duties service (poll proposer/attester duties), block service
(propose at slot start, proposers-first ordering block_service.rs:144-178),
attestation service (attest at slot/3, aggregate at 2*slot/3), preparation
and doppelganger services. Synchronous tick-driven design: `on_slot(slot)`
performs the full slot's duties (the async scheduling shell lives in the
runtime layer); works against any BeaconNodeInterface (in-process chain or
HTTP client) through BeaconNodeFallback.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import bls
from ..specs.chain_spec import ChainSpec
from ..ssz import htr
from .fallback import BeaconNodeFallback
from .slashing_protection import SlashingError
from .validator_store import ValidatorStore


class BeaconNodeInterface:
    """What the VC needs from a BN (common/eth2 client equivalent)."""

    def is_healthy(self) -> bool: ...

    def get_proposer_duties(self, epoch: int) -> list[tuple[int, int]]:
        """[(slot, validator_index)]"""

    def get_attester_duties(self, epoch: int, indices: list[int]) -> list:
        """[(slot, committee_index, validator_index, committee_len,
            position)]"""

    def get_validator_index(self, pubkey: bytes) -> int | None: ...

    def produce_block(self, slot: int, randao_reveal: bytes): ...

    def publish_block(self, signed_block) -> None: ...

    def attestation_data(self, slot: int, committee_index: int): ...

    def publish_attestation(self, attestation) -> None: ...

    def publish_aggregate(self, signed_aggregate) -> None: ...

    def head_fork_version(self) -> bytes: ...

    def seen_liveness(self, indices: list[int], epoch: int) -> list[bool]:
        """Doppelganger liveness data."""


@dataclass
class DoppelgangerState:
    """Refuse to sign for 2 epochs while watching for our keys being live
    elsewhere (doppelganger_service.rs:1-40)."""
    enabled: bool = False
    start_epoch: int = 0
    safe: bool = True

    def update(self, epoch: int, any_live: bool) -> None:
        if not self.enabled:
            return
        if any_live:
            self.safe = False
        elif epoch >= self.start_epoch + 2:
            self.safe = True

    def allows_signing(self, epoch: int) -> bool:
        if not self.enabled:
            return True
        return self.safe and epoch >= self.start_epoch + 2


class ValidatorClient:
    def __init__(self, spec: ChainSpec, store: ValidatorStore,
                 beacon_nodes: BeaconNodeFallback,
                 doppelganger_protection: bool = False):
        self.spec = spec
        self.store = store
        self.nodes = beacon_nodes
        self.doppelganger = DoppelgangerState(enabled=doppelganger_protection)
        self._duties: dict[int, list] = {}          # epoch -> attester duties
        self._proposers: dict[int, list] = {}       # epoch -> proposer duties
        self._indices: dict[bytes, int] = {}
        self.published_blocks = 0
        self.published_attestations = 0
        self.published_aggregates = 0
        self.published_sync_messages = 0
        # preparation service (validator_client/src/preparation_service.rs)
        self.fee_recipients: dict[bytes, bytes] = {}   # pubkey -> 20B
        self.default_fee_recipient: bytes | None = None
        self.builder_proposals = False
        self.gas_limit = 30_000_000
        self.graffiti: dict[bytes, str] = {}   # keymanager per-key graffiti
        self._prepared_epoch = -1

    # -- duties --------------------------------------------------------------

    def update_duties(self, epoch: int) -> None:
        for pk in self.store.voting_pubkeys():
            if pk not in self._indices:
                idx = self.nodes.first_success("get_validator_index", pk)
                if idx is not None:
                    self._indices[pk] = idx
        indices = list(self._indices.values())
        for e in (epoch, epoch + 1):
            self._duties[e] = self.nodes.first_success(
                "get_attester_duties", e, indices)
            self._proposers[e] = self.nodes.first_success(
                "get_proposer_duties", e)
        try:
            self.store.set_fork_version(
                self.nodes.first_success("head_fork_version"))
        except Exception:
            pass

    def _pubkey_for(self, validator_index: int) -> bytes | None:
        for pk, i in self._indices.items():
            if i == validator_index:
                return pk
        return None

    # -- slot work -----------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        spe = self.spec.preset.slots_per_epoch
        epoch = slot // spe
        if epoch not in self._duties or epoch + 1 not in self._duties:
            self.update_duties(epoch)
        if self.doppelganger.enabled:
            live = self.nodes.first_success(
                "seen_liveness", list(self._indices.values()), epoch)
            self.doppelganger.update(epoch, any(live))
            if not self.doppelganger.allows_signing(epoch):
                return
        if epoch > self._prepared_epoch:
            self.prepare_proposers(epoch)
        self.propose_if_due(slot)
        self.attest(slot)
        self.aggregate(slot)
        self.sync_committee_duty(slot)

    def sign_voluntary_exit(self, pubkey: bytes, validator_index: int,
                            epoch: int) -> dict:
        """Keymanager POST /eth/v1/validator/{pubkey}/voluntary_exit."""
        from ..containers import get_types
        T = get_types(self.spec.preset)
        msg = T.VoluntaryExit(epoch=epoch, validator_index=validator_index)
        sig = self.store.sign_voluntary_exit(pubkey, msg)
        return {"message": {"epoch": str(epoch),
                            "validator_index": str(validator_index)},
                "signature": "0x" + sig.hex()}

    def _fee_recipient(self, pubkey: bytes) -> bytes | None:
        return self.fee_recipients.get(pubkey, self.default_fee_recipient)

    def prepare_proposers(self, epoch: int) -> None:
        """Preparation service: push fee recipients (and, when builder
        proposals are enabled, signed validator registrations) to the BN
        once per epoch (preparation_service.rs)."""
        entries = []
        for pk, idx in self._indices.items():
            fee = self._fee_recipient(pk)
            if fee is not None:
                entries.append({"validator_index": idx,
                                "fee_recipient": "0x" + fee.hex()})
        if entries:
            try:
                self.nodes.first_success("prepare_beacon_proposer", entries)
            except Exception:
                return              # retry next slot, not next epoch
        if self.builder_proposals:
            regs = []
            import time as _time
            for pk in self.store.voting_pubkeys():
                fee = self._fee_recipient(pk) or b"\x00" * 20
                msg = {"fee_recipient": "0x" + fee.hex(),
                       "gas_limit": self.gas_limit,
                       "timestamp": int(_time.time()),
                       "pubkey": "0x" + pk.hex()}
                regs.append({
                    "message": msg,
                    "signature": "0x" + self.store.sign_validator_registration(
                        pk, msg).hex()})
            if regs:
                try:
                    self.nodes.first_success("register_validator", regs)
                except Exception:
                    return
        self._prepared_epoch = epoch

    def sync_committee_duty(self, slot: int) -> None:
        """Sign the head root with every of our validators in the current
        sync committee (sync_committee_service.rs)."""
        from ..containers import get_types
        T = get_types(self.spec.preset)
        try:
            members = self.nodes.first_success(
                "get_sync_duties", slot // self.spec.preset.slots_per_epoch,
                list(self._indices.values()))
            if not members:
                return
            head_root = self.nodes.first_success("head_root")
        except Exception as e:
            import logging
            logging.getLogger("lighthouse_tpu.vc").warning(
                "sync committee duty skipped: %r", e)
            return
        for vi in members:
            pk = self._pubkey_for(vi)
            if pk is None:
                continue
            sig = self.store.sign_sync_committee_message(pk, head_root)
            msg = T.SyncCommitteeMessage(
                slot=slot, beacon_block_root=head_root,
                validator_index=vi, signature=sig)
            self.nodes.broadcast("publish_sync_committee_message", msg)
            self.published_sync_messages += 1

    def propose_if_due(self, slot: int) -> None:
        spe = self.spec.preset.slots_per_epoch
        for duty_slot, validator_index in self._proposers.get(
                slot // spe, []):
            if duty_slot != slot:
                continue
            pk = self._pubkey_for(validator_index)
            if pk is None:
                continue
            reveal = self.store.randao_reveal(pk, slot // spe)
            graffiti = None
            if self.graffiti.get(pk):
                graffiti = self.graffiti[pk].encode()[:32].ljust(32, b"\0")
            try:
                block = self.nodes.first_success("produce_block", slot,
                                                 reveal, graffiti)
                sig = self.store.sign_block(pk, block)
            except SlashingError:
                continue
            except Exception:
                continue  # BN production failure must not kill the VC
            signed = self._signed_block(block, sig)
            self.nodes.broadcast("publish_block", signed)
            self.published_blocks += 1

    def _signed_block(self, block, sig: bytes):
        from ..containers import get_types
        T = get_types(self.spec.preset)
        fork = self.spec.fork_name_at_slot(block.slot)
        return T.SignedBeaconBlock[fork](message=block, signature=sig)

    def attest(self, slot: int) -> None:
        spe = self.spec.preset.slots_per_epoch
        from ..containers import get_types
        T = get_types(self.spec.preset)
        for duty in self._duties.get(slot // spe, []):
            duty_slot, committee_index, validator_index, committee_len, \
                position = duty
            if duty_slot != slot:
                continue
            pk = self._pubkey_for(validator_index)
            if pk is None:
                continue
            data = self.nodes.first_success("attestation_data", slot,
                                            committee_index)
            try:
                sig = self.store.sign_attestation(pk, data)
            except SlashingError:
                continue
            bits = [i == position for i in range(committee_len)]
            att = T.Attestation(aggregation_bits=bits, data=data,
                                signature=sig)
            self.nodes.broadcast("publish_attestation", att)
            self.published_attestations += 1

    def aggregate(self, slot: int) -> None:
        """Aggregation duty at 2/3 slot (attestation_service.rs): selection
        proof decides aggregators; aggregate from the BN's pool."""
        from ..chain.attestation_verification import is_aggregator
        from ..containers import get_types
        T = get_types(self.spec.preset)
        spe = self.spec.preset.slots_per_epoch
        for duty in self._duties.get(slot // spe, []):
            duty_slot, committee_index, validator_index, committee_len, \
                _position = duty
            if duty_slot != slot:
                continue
            pk = self._pubkey_for(validator_index)
            if pk is None:
                continue
            proof = self.store.selection_proof(pk, slot)
            if not is_aggregator(committee_len, proof):
                continue
            try:
                aggregate = self.nodes.first_success(
                    "get_aggregate", slot, committee_index)
            except Exception:
                continue
            if aggregate is None:
                continue
            msg = T.AggregateAndProof(aggregator_index=validator_index,
                                      aggregate=aggregate,
                                      selection_proof=proof)
            sig = self.store.sign_aggregate_and_proof(pk, msg)
            signed = T.SignedAggregateAndProof(message=msg, signature=sig)
            self.nodes.broadcast("publish_aggregate", signed)
            self.published_aggregates += 1
