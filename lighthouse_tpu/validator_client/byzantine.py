"""Byzantine validator-client wrapper for adversarial scenarios.

Wraps a ValidatorClient and misbehaves on command.  The honest client's
ValidatorStore refuses slashable signatures (slashing_protection.py), so
the equivocating paths here sign RAW — the exact bypass a compromised or
buggy remote signer represents.  Everything published still goes through
the beacon node's normal publish API: the second (equivocating) message
is REJECTED from gossip there, which is precisely the choke point where
gossip verification authenticates it and hands it to the slasher.

Modes
-----
``honest``
    Pure delegation.
``silent``
    Withhold attestations/aggregates/sync messages but keep proposing:
    an offline-voter stake mass (the long non-finality scenario).
``double_propose``
    Produce and publish TWO blocks per proposal duty (second with
    different graffiti, hence a different body root).
``double_vote``
    Publish TWO attestations per attester duty with the same target but
    different head roots.
"""
from __future__ import annotations

from ..crypto import bls
from ..specs.chain_spec import compute_signing_root
from ..specs.constants import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER
from ..ssz import htr
from .client import ValidatorClient

EVIL_GRAFFITI = b"equivocation!".ljust(32, b"\x00")


def raw_sign_block(store, pubkey: bytes, block) -> bytes:
    """Proposer signature WITHOUT the slashing-protection gate."""
    domain = store._domain(DOMAIN_BEACON_PROPOSER)
    return store._sign(pubkey, compute_signing_root(htr(block), domain))


def raw_sign_attestation(store, pubkey: bytes, data) -> bytes:
    """Attester signature WITHOUT the slashing-protection gate."""
    domain = store._domain(DOMAIN_BEACON_ATTESTER)
    return store._sign(pubkey, compute_signing_root(htr(data), domain))


class ByzantineValidatorClient:
    """Delegating wrapper; only the mode-relevant duties are overridden,
    so duty scheduling, fallback routing and counters stay the inner
    client's."""

    def __init__(self, inner: ValidatorClient, mode: str = "honest"):
        if mode not in ("honest", "silent", "double_propose",
                        "double_vote"):
            raise ValueError(f"unknown byzantine mode {mode!r}")
        self._inner = inner
        self.mode = mode
        self.equivocations = 0      # second messages actually published

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- silent: withhold votes, keep proposing ------------------------------

    def attest(self, slot: int) -> None:
        if self.mode == "silent":
            return
        if self.mode == "double_vote":
            self._double_vote(slot)
            return
        self._inner.attest(slot)

    def aggregate(self, slot: int) -> None:
        if self.mode == "silent":
            return
        self._inner.aggregate(slot)

    def sync_committee_duty(self, slot: int) -> None:
        if self.mode == "silent":
            return
        self._inner.sync_committee_duty(slot)

    def propose_if_due(self, slot: int) -> None:
        if self.mode == "double_propose":
            self._double_propose(slot)
            return
        self._inner.propose_if_due(slot)

    # -- equivocation --------------------------------------------------------

    def _double_propose(self, slot: int) -> None:
        vc = self._inner
        spe = vc.spec.preset.slots_per_epoch
        for duty_slot, validator_index in vc._proposers.get(slot // spe,
                                                            []):
            if duty_slot != slot:
                continue
            pk = vc._pubkey_for(validator_index)
            if pk is None:
                continue
            reveal = vc.store.randao_reveal(pk, slot // spe)
            try:
                # produce BOTH candidates before publishing either, so
                # the second build is not a child of the first
                block_a = vc.nodes.first_success("produce_block", slot,
                                                 reveal, None)
                block_b = vc.nodes.first_success("produce_block", slot,
                                                 reveal, EVIL_GRAFFITI)
            except Exception:
                continue
            signed_a = vc._signed_block(block_a,
                                        raw_sign_block(vc.store, pk,
                                                       block_a))
            signed_b = vc._signed_block(block_b,
                                        raw_sign_block(vc.store, pk,
                                                       block_b))
            vc.nodes.broadcast("publish_block", signed_a)
            vc.published_blocks += 1
            if htr(block_b) != htr(block_a):
                # the BN rejects this from gossip (repeat proposal) and
                # feeds the slasher; broadcast() swallows the 400
                vc.nodes.broadcast("publish_block", signed_b)
                self.equivocations += 1

    def _double_vote(self, slot: int) -> None:
        from ..containers import get_types
        vc = self._inner
        T = get_types(vc.spec.preset)
        spe = vc.spec.preset.slots_per_epoch
        for duty in vc._duties.get(slot // spe, []):
            duty_slot, committee_index, validator_index, committee_len, \
                position = duty
            if duty_slot != slot:
                continue
            pk = vc._pubkey_for(validator_index)
            if pk is None:
                continue
            data = vc.nodes.first_success("attestation_data", slot,
                                          committee_index)
            bits = [i == position for i in range(committee_len)]
            att_a = T.Attestation(
                aggregation_bits=bits, data=data,
                signature=raw_sign_attestation(vc.store, pk, data))
            vc.nodes.broadcast("publish_attestation", att_a)
            vc.published_attestations += 1
            # same (source, target) but a different vote: point the head
            # vote at the target block instead of the true head — still a
            # known block, so only the double-vote check can catch it
            if data.beacon_block_root == data.target.root:
                continue
            data_b = T.AttestationData(
                slot=data.slot, index=data.index,
                beacon_block_root=data.target.root,
                source=data.source, target=data.target)
            att_b = T.Attestation(
                aggregation_bits=bits, data=data_b,
                signature=raw_sign_attestation(vc.store, pk, data_b))
            vc.nodes.broadcast("publish_attestation", att_b)
            self.equivocations += 1
