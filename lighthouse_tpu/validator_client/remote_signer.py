"""Web3Signer-style remote signing (signing_method.rs:80-95).

The VC holds only the PUBLIC key for remote validators; signing requests
go to the signer over HTTP:

  POST {url}/api/v1/eth2/sign/{pubkey}   body: {"signing_root": "0x.."}
  -> {"signature": "0x.."}

MockWeb3Signer is the test-side server holding the secret keys (the
reference tests against a real Web3Signer container; same surface).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import request as urlrequest


class RemoteSignerError(Exception):
    pass


def remote_sign(url: str, pubkey: bytes, signing_root: bytes,
                timeout: float = 5.0) -> bytes:
    req = urlrequest.Request(
        f"{url.rstrip('/')}/api/v1/eth2/sign/0x{pubkey.hex()}",
        data=json.dumps({"signing_root": "0x" + signing_root.hex()}
                        ).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urlrequest.urlopen(req, timeout=timeout) as r:
            out = json.loads(r.read())
        return bytes.fromhex(out["signature"][2:])
    except Exception as e:
        raise RemoteSignerError(str(e)) from None


class MockWeb3Signer:
    """Holds secret keys; signs any root it is asked to (the slashing
    protection lives VC-side, as with the real Web3Signer default)."""

    def __init__(self):
        self._keys: dict[bytes, int] = {}
        self.requests: list[tuple[bytes, bytes]] = []
        self._server: ThreadingHTTPServer | None = None

    def add_key(self, sk: int) -> bytes:
        from ..crypto import bls
        pk = bls.sk_to_pk(sk)
        self._keys[pk] = sk
        return pk

    def start(self, port: int = 0) -> str:
        signer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                from ..crypto import bls
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                parts = self.path.strip("/").split("/")
                resp, code = {"message": "not found"}, 404
                if parts[:4] == ["api", "v1", "eth2", "sign"] and \
                        len(parts) == 5:
                    pk = bytes.fromhex(parts[4][2:])
                    sk = signer._keys.get(pk)
                    root = bytes.fromhex(body["signing_root"][2:])
                    if sk is not None:
                        signer.requests.append((pk, root))
                        sig = bls.sign(sk, root)
                        resp, code = {"signature": "0x" + sig.hex()}, 200
                out = json.dumps(resp).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return f"http://127.0.0.1:{self._server.server_port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
        if getattr(self, "_thread", None) is not None:
            self._thread.join(timeout=2)
            self._thread = None
