"""ValidatorStore: the signing facade gated by slashing protection.

Equivalent of /root/reference/validator_client/src/validator_store.rs:61 and
signing_method.rs:80-95 (LocalKeystore; Web3Signer slot kept as an interface).
"""
from __future__ import annotations

from ..crypto import bls
from ..specs.chain_spec import ChainSpec, compute_domain, compute_signing_root
from ..specs.constants import (
    DOMAIN_AGGREGATE_AND_PROOF, DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO, DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE, DOMAIN_VOLUNTARY_EXIT,
)
from ..ssz import hash_tree_root, htr, uint64
from .slashing_protection import SlashingDatabase, SlashingError


class SigningMethod:
    LOCAL_KEYSTORE = "local_keystore"
    WEB3SIGNER = "web3signer"


class ValidatorStore:
    def __init__(self, spec: ChainSpec, genesis_validators_root: bytes,
                 slashing_db: SlashingDatabase | None = None):
        self.spec = spec
        self.genesis_validators_root = genesis_validators_root
        self.slashing_db = slashing_db or SlashingDatabase()
        self._keys: dict[bytes, int] = {}  # pubkey -> sk
        self._fork_version = spec.genesis_fork_version

    def add_validator(self, sk: int) -> bytes:
        pk = bls.sk_to_pk(sk)
        self._keys[pk] = sk
        self.slashing_db.register_validator(pk)
        return pk

    def add_remote_validator(self, pubkey: bytes, url: str) -> None:
        """Web3Signer-backed key: only the URL is held locally
        (signing_method.rs remote path); slashing protection stays here."""
        if not hasattr(self, "_remote_keys"):
            self._remote_keys: dict[bytes, str] = {}
        self._remote_keys[pubkey] = url
        self.slashing_db.register_validator(pubkey)

    def remove_remote_validator(self, pubkey: bytes) -> None:
        getattr(self, "_remote_keys", {}).pop(pubkey, None)

    def voting_pubkeys(self) -> list[bytes]:
        return list(self._keys) + list(getattr(self, "_remote_keys", {}))

    def set_fork_version(self, version: bytes) -> None:
        self._fork_version = version

    def _domain(self, domain_type: int) -> bytes:
        return compute_domain(domain_type, self._fork_version,
                              self.genesis_validators_root)

    def _sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        sk = self._keys.get(pubkey)
        if sk is not None:
            return bls.sign(sk, signing_root)
        url = getattr(self, "_remote_keys", {}).get(pubkey)
        if url is not None:
            from .remote_signer import remote_sign
            return remote_sign(url, pubkey, signing_root)
        raise SlashingError("unknown validator key")

    # -- gated signing -------------------------------------------------------

    def sign_block(self, pubkey: bytes, block) -> bytes:
        domain = self._domain(DOMAIN_BEACON_PROPOSER)
        signing_root = compute_signing_root(htr(block), domain)
        self.slashing_db.check_and_insert_block_proposal(
            pubkey, block.slot, signing_root)
        return self._sign(pubkey, signing_root)

    def sign_attestation(self, pubkey: bytes, data) -> bytes:
        domain = self._domain(DOMAIN_BEACON_ATTESTER)
        signing_root = compute_signing_root(htr(data), domain)
        self.slashing_db.check_and_insert_attestation(
            pubkey, data.source.epoch, data.target.epoch, signing_root)
        return self._sign(pubkey, signing_root)

    # -- ungated signing (not slashable) -------------------------------------

    def sign_validator_registration(self, pubkey: bytes,
                                    message: dict) -> bytes:
        """Builder-specs SignedValidatorRegistration (signing_method.rs
        builder path).  Domain uses the GENESIS fork version and a zero
        genesis_validators_root per the builder specs."""
        import hashlib
        from ..specs.constants import DOMAIN_APPLICATION_BUILDER
        domain = compute_domain(DOMAIN_APPLICATION_BUILDER,
                                self.spec.genesis_fork_version, b"\x00" * 32)
        # miniature registration root: no dedicated SSZ container type —
        # a canonical field hash stands in (mock builder checks bytes only)
        root = hashlib.sha256(
            bytes.fromhex(message["fee_recipient"][2:])
            + int(message["gas_limit"]).to_bytes(8, "little")
            + int(message["timestamp"]).to_bytes(8, "little")
            + bytes.fromhex(message["pubkey"][2:])).digest()
        return self._sign(pubkey, compute_signing_root(root, domain))

    def randao_reveal(self, pubkey: bytes, epoch: int) -> bytes:
        domain = self._domain(DOMAIN_RANDAO)
        return self._sign(pubkey, compute_signing_root(
            hash_tree_root(uint64, epoch), domain))

    def selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        domain = self._domain(DOMAIN_SELECTION_PROOF)
        return self._sign(pubkey, compute_signing_root(
            hash_tree_root(uint64, slot), domain))

    def sign_aggregate_and_proof(self, pubkey: bytes, message) -> bytes:
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF)
        return self._sign(pubkey, compute_signing_root(htr(message), domain))

    def sign_voluntary_exit(self, pubkey: bytes, exit_message) -> bytes:
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT)
        return self._sign(pubkey, compute_signing_root(htr(exit_message),
                                                       domain))

    def sign_sync_committee_message(self, pubkey: bytes,
                                    block_root: bytes) -> bytes:
        domain = self._domain(DOMAIN_SYNC_COMMITTEE)
        return self._sign(pubkey, compute_signing_root(block_root, domain))
