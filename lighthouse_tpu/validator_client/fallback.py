"""Multi-beacon-node failover.

Equivalent of /root/reference/validator_client/src/beacon_node_fallback.rs:
an ordered BN list, health-checked and re-sorted; operations walk the list
until one succeeds; broadcast-capable for publish operations.
"""
from __future__ import annotations

import time


class BeaconNodeFallback:
    def __init__(self, nodes: list):
        self.nodes = list(nodes)
        self.health: dict[int, bool] = {i: True for i in range(len(nodes))}
        self.last_check: float = 0.0

    def check_health(self) -> None:
        for i, node in enumerate(self.nodes):
            try:
                ok = node.is_healthy()
            except Exception:
                ok = False
            self.health[i] = ok
        self.last_check = time.monotonic()
        # healthy nodes first, stable order otherwise
        order = sorted(range(len(self.nodes)),
                       key=lambda i: (not self.health[i], i))
        self.nodes = [self.nodes[i] for i in order]
        self.health = {i: self.health.get(j, True)
                       for i, j in enumerate(order)}

    def first_success(self, fn_name: str, *args, **kwargs):
        """Try each node in order; return the first success."""
        last_err: Exception | None = None
        for i, node in enumerate(self.nodes):
            try:
                out = getattr(node, fn_name)(*args, **kwargs)
                self.health[i] = True
                return out
            except Exception as e:
                self.health[i] = False
                last_err = e
        raise last_err if last_err else RuntimeError("no beacon nodes")

    def broadcast(self, fn_name: str, *args, **kwargs) -> int:
        """Publish to every node; returns success count."""
        ok = 0
        for node in self.nodes:
            try:
                getattr(node, fn_name)(*args, **kwargs)
                ok += 1
            except Exception:
                pass
        return ok
