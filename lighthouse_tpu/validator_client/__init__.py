"""Validator client (the parallel stack, SURVEY.md VC row).

Equivalent of /root/reference/validator_client (23.1k LoC): per-slot duty
machine — duties polling, block proposal, attestation + aggregation,
sync-committee duty, preparation — over a `ValidatorStore` signing facade
gated by SQLite slashing protection (EIP-3076), with multi-BN failover.
"""
from .slashing_protection import SlashingDatabase, SlashingError
from .validator_store import ValidatorStore
from .client import ValidatorClient, BeaconNodeInterface
from .fallback import BeaconNodeFallback
from .http_client import BeaconNodeHttpClient
from .byzantine import ByzantineValidatorClient
