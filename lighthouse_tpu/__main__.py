"""CLI (L10): `python -m lighthouse_tpu <subcommand>`.

Equivalent of /root/reference/lighthouse/src/main.rs subcommand dispatch
(:412-416): beacon_node, validator_client, account_manager, database_manager,
plus lcli-style dev tools. Flags fold into typed configs
(beacon_node/src/{cli,config}.rs).
"""
from __future__ import annotations

import argparse
import json
import sys


def build_parser():
    parser = argparse.ArgumentParser(
        prog="lighthouse_tpu",
        description="TPU-native Ethereum consensus client")
    from .specs.networks import NETWORKS
    parser.add_argument("--network", default="minimal",
                        choices=sorted(NETWORKS),
                        help="baked-in network config")
    parser.add_argument("--testnet-dir", default=None,
                        help="custom network dir with config.yaml "
                             "(overrides --network)")
    parser.add_argument("--log-level", default="INFO")
    sub = parser.add_subparsers(dest="cmd", required=True)

    bn = sub.add_parser("beacon_node", aliases=["bn", "beacon"])
    bn.add_argument("--datadir", default=None)
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--disable-http", action="store_true",
                    help="do not start the HTTP API server")
    bn.add_argument("--metrics", action="store_true")
    bn.add_argument("--metrics-port", type=int, default=5054)
    bn.add_argument("--listen-address", default="127.0.0.1",
                    help="libp2p + discovery listen address")
    bn.add_argument("--target-peers", type=int, default=16)
    bn.add_argument("--discovery-port", type=int, default=0,
                    help="discv5 UDP port (0 = ephemeral)")
    bn.add_argument("--upnp", action="store_true",
                    help="attempt UPnP port mapping at startup")
    bn.add_argument("--subscribe-all-subnets", action="store_true",
                    help="advertise + subscribe every attestation subnet")
    bn.add_argument("--graffiti", default="",
                    help="ascii graffiti for locally produced blocks")
    bn.add_argument("--suggested-fee-recipient", default=None,
                    help="0x-prefixed 20-byte default fee recipient")
    bn.add_argument("--snapshot-cache-size", type=int, default=8)
    bn.add_argument("--reorg-threshold", type=int, default=20,
                    help="late-block re-org weight threshold (percent)")
    bn.add_argument("--disable-light-client-server", action="store_true")
    bn.add_argument("--validator-monitor-pubkeys", default="",
                    help="comma-separated 0x pubkeys to monitor")
    bn.add_argument("--purge-db", action="store_true",
                    help="wipe the datadir's chain database on startup")
    bn.add_argument("--port", type=int, default=9000,
                    help="p2p listen port")
    bn.add_argument("--boot-nodes", default="",
                    help="comma-separated host:port list")
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--crypto-backend", default="python",
                    choices=["python", "fake", "tpu", "cpp"])
    bn.add_argument("--interop-validators", type=int, default=0)
    bn.add_argument("--genesis-time", type=int, default=None)
    bn.add_argument("--checkpoint-state", default=None,
                    help="SSZ state file for checkpoint sync")
    bn.add_argument("--checkpoint-block", default=None)
    bn.add_argument("--dump-config", action="store_true")

    vc = sub.add_parser("validator_client", aliases=["vc"])
    vc.add_argument("--beacon-nodes", default="http://127.0.0.1:5052")
    vc.add_argument("--interop-validators", type=int, default=0)
    vc.add_argument("--slashing-db", default=":memory:")

    am = sub.add_parser("account_manager", aliases=["am", "account"])
    am_sub = am.add_subparsers(dest="am_cmd", required=True)
    am_new = am_sub.add_parser("validator_new")
    am_new.add_argument("--count", type=int, default=1)
    am_new.add_argument("--out", default="keystores")
    am_new.add_argument("--password", default="")
    am_wnew = am_sub.add_parser("wallet_new", help="EIP-2386 hd wallet")
    am_wnew.add_argument("--name", required=True)
    am_wnew.add_argument("--password", default="")
    am_wnew.add_argument("--wallet-dir", default="wallets")
    am_wlist = am_sub.add_parser("wallet_list")
    am_wlist.add_argument("--wallet-dir", default="wallets")
    am_vc = am_sub.add_parser("validator_create",
                              help="derive next validator from a wallet")
    am_vc.add_argument("--name", required=True)
    am_vc.add_argument("--password", default="")
    am_vc.add_argument("--keystore-password", default="")
    am_vc.add_argument("--wallet-dir", default="wallets")
    am_vc.add_argument("--out", default="keystores")

    bnode = sub.add_parser("boot_node", help="standalone discovery bootnode")
    bnode.add_argument("--host", default="127.0.0.1")
    bnode.add_argument("--boot-port", type=int, default=9100)

    dev = sub.add_parser("dev", help="lcli-style dev tools")
    dev_sub = dev.add_subparsers(dest="dev_cmd", required=True)
    tr = dev_sub.add_parser("transition-blocks")
    tr.add_argument("--pre", required=True, help="pre-state SSZ (fork byte"
                    " + state)")
    tr.add_argument("--block", required=True)
    tr.add_argument("--out", required=True)
    tr.add_argument("--no-signature-verification", action="store_true")
    sk = dev_sub.add_parser("skip-slots")
    sk.add_argument("--pre", required=True)
    sk.add_argument("--slots", type=int, required=True)
    sk.add_argument("--out", required=True)
    sr = dev_sub.add_parser("state-root")
    sr.add_argument("--state", required=True)
    br = dev_sub.add_parser("block-root")
    br.add_argument("--block", required=True)
    gi = dev_sub.add_parser("interop-genesis")
    gi.add_argument("--validators", type=int, default=64)
    gi.add_argument("--genesis-time", type=int, default=0)
    gi.add_argument("--out", required=True)

    dbm = sub.add_parser("database_manager", aliases=["db"])
    dbm.add_argument("--datadir", required=True)
    dbm_sub = dbm.add_subparsers(dest="db_cmd", required=True)
    dbm_sub.add_parser("version")
    dbm_sub.add_parser("inspect")
    dbm_sub.add_parser("compact")

    # validator_manager: bulk create/import/move (the reference's
    # validator_manager crate surface)
    vm = sub.add_parser("validator_manager", aliases=["vm"],
                        help="bulk validator lifecycle tooling")
    vm_sub = vm.add_subparsers(dest="vm_cmd", required=True)
    vm_create = vm_sub.add_parser("create",
                                  help="derive keystores from a seed")
    vm_create.add_argument("--seed-hex", required=True)
    vm_create.add_argument("--count", type=int, required=True)
    vm_create.add_argument("--first-index", type=int, default=0)
    vm_create.add_argument("--out-dir", required=True)
    vm_create.add_argument("--password", default="lighthouse-tpu")
    vm_import = vm_sub.add_parser("import",
                                  help="import keystores into a datadir")
    vm_import.add_argument("--keystore-dir", required=True)
    vm_import.add_argument("--password", default="lighthouse-tpu")
    vm_import.add_argument("--datadir", required=True)
    vm_move = vm_sub.add_parser(
        "move", help="move validators between datadirs w/ slashing history")
    vm_move.add_argument("--src-datadir", required=True)
    vm_move.add_argument("--dst-datadir", required=True)
    vm_move.add_argument("--keystore-dir", required=True,
                         help="dir holding the keystores to move")
    vm_move.add_argument("--password", default="lighthouse-tpu")
    vm_move.add_argument("--pubkeys", required=True,
                         help="comma-separated 0x pubkeys")
    return parser


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.testnet_dir:
        from .specs.networks import load_testnet_dir
        spec = load_testnet_dir(args.testnet_dir)
    else:
        from .specs.networks import network_spec
        spec = network_spec(args.network)

    if args.cmd in ("beacon_node", "bn", "beacon"):
        return _run_beacon_node(spec, args)
    if args.cmd in ("validator_client", "vc"):
        return _run_validator_client(spec, args)
    if args.cmd in ("account_manager", "am", "account"):
        return _run_account_manager(spec, args)
    if args.cmd in ("database_manager", "db"):
        return _run_database_manager(spec, args)
    if args.cmd == "dev":
        return _run_dev(spec, args)
    if args.cmd == "boot_node":
        from .network.discovery import main as boot_main
        return boot_main(["--host", args.host, "--port",
                          str(args.boot_port)])
    if args.cmd in ("validator_manager", "vm"):
        return _run_validator_manager(spec, args)
    return 1


def _run_validator_manager(spec, args):
    from . import validator_manager as vman
    from .validator_client import ValidatorStore

    def _store(datadir):
        import os
        from .validator_client import SlashingDatabase
        os.makedirs(datadir, exist_ok=True)
        db = SlashingDatabase(os.path.join(datadir,
                                           "slashing_protection.sqlite"))
        return ValidatorStore(spec, b"\x00" * 32, slashing_db=db)

    if args.vm_cmd == "create":
        out = vman.create_validators(
            bytes.fromhex(args.seed_hex.removeprefix("0x")), args.count,
            args.out_dir, args.password.encode(),
            first_index=args.first_index)
        print(f"created {len(out)} keystores in {args.out_dir}")
        return 0
    if args.vm_cmd == "import":
        store = _store(args.datadir)
        n = vman.import_validators(args.keystore_dir,
                                   args.password.encode(), store)
        print(f"imported {n} validators into {args.datadir}")
        return 0
    if args.vm_cmd == "move":
        src = _store(args.src_datadir)
        dst = _store(args.dst_datadir)
        # keys live in keystores, not the datadir: load them into the
        # source store first (the reference's move flow talks to a live
        # VC keymanager; the offline equivalent is keystore-dir + both
        # slashing databases)
        vman.import_validators(args.keystore_dir, args.password.encode(),
                               src)
        pubkeys = [bytes.fromhex(p.strip().removeprefix("0x"))
                   for p in args.pubkeys.split(",") if p.strip()]
        n = vman.move_validators(src, dst, pubkeys, b"\x00" * 32)
        print(f"moved {n} validators")
        return 0
    return 1


def _load_state(spec, path):
    from .containers import get_types
    from .containers.state import BeaconState
    from .specs.chain_spec import ForkName
    raw = open(path, "rb").read()
    return BeaconState.from_ssz_bytes(raw[1:], get_types(spec.preset), spec,
                                      ForkName(raw[0]))


def _dump_state(state, path):
    with open(path, "wb") as f:
        f.write(bytes([state.fork_name.value]) + state.serialize())


def _run_dev(spec, args):
    from .containers import get_types
    from .specs.chain_spec import ForkName
    from .ssz import deserialize, htr
    T = get_types(spec.preset)
    if args.dev_cmd == "transition-blocks":
        from .state_transition import per_block_processing, process_slots
        from .state_transition.block import VerifySignatures
        state = _load_state(spec, args.pre)
        braw = open(args.block, "rb").read()
        signed = deserialize(
            T.SignedBeaconBlock[ForkName(braw[0])].ssz_type, braw[1:])
        process_slots(state, signed.message.slot)
        per_block_processing(
            state, signed,
            VerifySignatures.FALSE if args.no_signature_verification
            else VerifySignatures.TRUE)
        _dump_state(state, args.out)
        print(json.dumps({"post_state_root":
                          "0x" + state.hash_tree_root().hex()}))
    elif args.dev_cmd == "skip-slots":
        from .state_transition import process_slots
        state = _load_state(spec, args.pre)
        process_slots(state, state.slot + args.slots)
        _dump_state(state, args.out)
        print(json.dumps({"slot": state.slot,
                          "state_root":
                          "0x" + state.hash_tree_root().hex()}))
    elif args.dev_cmd == "state-root":
        state = _load_state(spec, args.state)
        print(json.dumps({"slot": state.slot, "fork":
                          state.fork_name.name.lower(),
                          "root": "0x" + state.hash_tree_root().hex()}))
    elif args.dev_cmd == "block-root":
        braw = open(args.block, "rb").read()
        signed = deserialize(
            T.SignedBeaconBlock[ForkName(braw[0])].ssz_type, braw[1:])
        print(json.dumps({"slot": signed.message.slot,
                          "root": "0x" + htr(signed.message).hex()}))
    elif args.dev_cmd == "interop-genesis":
        from .crypto import bls
        from .state_transition import interop_genesis_state
        state = interop_genesis_state(
            spec, [bls.keygen_interop(i) for i in range(args.validators)],
            genesis_time=args.genesis_time)
        _dump_state(state, args.out)
        print(json.dumps({"validators": args.validators,
                          "genesis_validators_root":
                          "0x" + state.genesis_validators_root.hex()}))
    return 0


def _run_beacon_node(spec, args):
    from .client import ClientBuilder, Environment
    from .client.builder import ClientConfig
    from .network import NetworkConfig

    boot = []
    for hp in filter(None, args.boot_nodes.split(",")):
        host, _, port = hp.rpartition(":")
        boot.append((host or "127.0.0.1", int(port)))
    graffiti = args.graffiti.encode()[:32].ljust(32, b"\x00") \
        if args.graffiti else None
    fee_recipient = None
    if args.suggested_fee_recipient:
        try:
            fee_recipient = bytes.fromhex(
                args.suggested_fee_recipient.removeprefix("0x"))
        except ValueError:
            fee_recipient = b""
        if len(fee_recipient) != 20:
            print("error: --suggested-fee-recipient must be a 0x-prefixed"
                  " 20-byte hex address", file=sys.stderr)
            return 2
    monitor_pubkeys = [bytes.fromhex(p.strip().removeprefix("0x"))
                       for p in args.validator_monitor_pubkeys.split(",")
                       if p.strip()]
    cfg = ClientConfig(
        datadir=args.datadir, http_port=args.http_port,
        http_enabled=not args.disable_http,
        metrics_enabled=args.metrics, metrics_port=args.metrics_port,
        network=NetworkConfig(
            host=args.listen_address, port=args.port,
            target_peers=args.target_peers, boot_nodes=boot,
            upnp_enabled=args.upnp,
            subscribe_all_subnets=args.subscribe_all_subnets),
        discovery_port=args.discovery_port,
        graffiti=graffiti, suggested_fee_recipient=fee_recipient,
        snapshot_cache_size=args.snapshot_cache_size,
        reorg_threshold_pct=args.reorg_threshold,
        light_client_server=not args.disable_light_client_server,
        validator_monitor_pubkeys=monitor_pubkeys,
        purge_db=args.purge_db,
        slasher_enabled=args.slasher, crypto_backend=args.crypto_backend,
        interop_validator_count=args.interop_validators,
        genesis_time=args.genesis_time)
    if args.testnet_dir:
        from .specs.networks import testnet_genesis_state
        st = testnet_genesis_state(args.testnet_dir, spec)
        if st is not None:
            cfg.genesis_state = st
    if args.checkpoint_state:
        cfg.checkpoint_sync_state = open(args.checkpoint_state, "rb").read()
        if args.checkpoint_block:
            cfg.checkpoint_sync_block = \
                open(args.checkpoint_block, "rb").read()
    if args.dump_config:
        from .specs.networks import spec_to_config
        out = dict(vars(cfg))
        out["network"] = vars(cfg.network)
        out["spec"] = spec_to_config(spec)
        for k, v in out.items():
            if isinstance(v, bytes):
                out[k] = "0x" + v.hex()
            elif isinstance(v, list) and v and isinstance(v[0], bytes):
                out[k] = ["0x" + b.hex() for b in v]
        print(json.dumps(out, default=str))
        return 0
    env = Environment(args.log_level)
    client = ClientBuilder(spec, env).with_config(cfg).build()
    env.log.info("beacon node up: http=%s p2p=%s",
                 client.api_server.port if client.api_server else None,
                 client.network.port)
    reason = env.block_until_shutdown()
    env.log.info("shutting down: %s", reason)
    client.stop()
    return 0


def _run_validator_client(spec, args):
    import time as _time
    from .client import Environment
    from .crypto import bls
    from .validator_client import (
        BeaconNodeFallback, BeaconNodeHttpClient, SlashingDatabase,
        ValidatorClient, ValidatorStore,
    )
    env = Environment(args.log_level)
    clients = [BeaconNodeHttpClient(u.strip(), spec)
               for u in args.beacon_nodes.split(",") if u.strip()]
    nodes = BeaconNodeFallback(clients)
    genesis = clients[0]._req("GET", "/eth/v1/beacon/genesis")["data"]
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    genesis_time = int(genesis["genesis_time"])
    store = ValidatorStore(spec, gvr, SlashingDatabase(args.slashing_db))
    for i in range(args.interop_validators):
        store.add_validator(bls.keygen_interop(i))
    vc = ValidatorClient(spec, store, nodes)
    env.log.info("validator client: %d keys, %d beacon nodes",
                 args.interop_validators, len(clients))

    def loop():
        last = -1
        while not env.shutdown_requested():
            slot = max(0, int(_time.time() - genesis_time)
                       // spec.seconds_per_slot)
            if slot != last and _time.time() >= genesis_time:
                last = slot
                try:
                    vc.on_slot(slot)
                except Exception:
                    env.log.exception("slot duties failed")
            _time.sleep(0.25)
    env.spawn(loop, "vc-loop")
    env.block_until_shutdown()
    return 0


def _run_account_manager(spec, args):
    import os
    from .crypto import bls
    from .crypto.keystore import create_keystore
    if args.am_cmd == "wallet_new":
        from .crypto.wallet import WalletManager
        wm = WalletManager(args.wallet_dir)
        w = wm.create(args.name, args.password.encode())
        print(json.dumps({"name": w.name, "uuid": w.data["uuid"]}))
        return 0
    if args.am_cmd == "wallet_list":
        from .crypto.wallet import WalletManager
        print(json.dumps(WalletManager(args.wallet_dir).list()))
        return 0
    if args.am_cmd == "validator_create":
        from .crypto.wallet import WalletManager
        wm = WalletManager(args.wallet_dir)
        w = wm.open(args.name)
        ks = w.next_validator_keystore(args.password.encode(),
                                       args.keystore_password.encode())
        wm.save(w)                     # persist the nextaccount bump
        os.makedirs(args.out, exist_ok=True)
        path = os.path.join(args.out, f"keystore-{ks['pubkey'][:12]}.json")
        with open(path, "w") as f:
            json.dump(ks, f, indent=2)
        print(f"wrote {path}")
        return 0
    os.makedirs(args.out, exist_ok=True)
    for i in range(args.count):
        sk = bls.keygen_interop(i)
        pk = bls.sk_to_pk(sk)
        ks = create_keystore(sk, args.password.encode())
        path = os.path.join(args.out, f"keystore-{i}-{pk.hex()[:12]}.json")
        with open(path, "w") as f:
            json.dump(ks, f, indent=2)
        print(f"wrote {path}")
    return 0


def _run_database_manager(spec, args):
    from .store import HotColdDB, NativeKvStore
    import os
    db = HotColdDB(NativeKvStore(os.path.join(args.datadir, "chain_db")),
                   NativeKvStore(os.path.join(args.datadir, "freezer_db")),
                   spec)
    if args.db_cmd == "version":
        print(json.dumps({"schema_version": db.schema_version()}))
    elif args.db_cmd == "inspect":
        print(json.dumps({"split_slot": db.split.slot,
                          "hot_keys": len(db.hot) if hasattr(
                              db.hot, "__len__") else -1}))
    elif args.db_cmd == "compact":
        db.hot.compact()
        db.cold.compact()
        print("compacted")
    return 0


if __name__ == "__main__":
    sys.exit(main())
